"""Configuration tree for the TPU-native partitioner.

Mirrors the reference's nested plain-struct ``Context``
(``/root/reference/include/kaminpar-shm/kaminpar.h:610-622`` and the enums at
``kaminpar.h:66-605``): one dataclass per subsystem, presets construct the tree
fully in code (see :mod:`kaminpar_tpu.presets`), and the CLI binds flags
directly onto the fields.  Unlike the reference we keep the tree small and add
TPU-specific knobs (index dtype, device mesh shape) instead of TBB/NUMA ones.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from dataclasses import dataclass, field
from typing import Optional


class PartitioningMode(enum.Enum):
    """Orchestration scheme (reference: ``PartitioningMode``, kaminpar.h:66)."""

    DEEP = "deep"
    RB = "rb"
    KWAY = "kway"
    VCYCLE = "vcycle"


class ClusteringAlgorithm(enum.Enum):
    """Coarsening clusterer (reference: ``ClusteringAlgorithm``)."""

    NOOP = "noop"
    LP = "lp"
    HEM = "hem"


class DistClusteringAlgorithm(enum.Enum):
    """Distributed coarsening clusterer (reference: dist
    ClusteringAlgorithm, dkaminpar.h:73-78)."""

    GLOBAL_LP = "global-lp"
    # Shard-local clusters only: exchange-free, conflict-free rounds
    # (local_lp_clusterer.cc); never merges across shard boundaries.
    LOCAL_LP = "local-lp"
    # LOCAL_LP rounds first, then GLOBAL_LP rounds on what remains — the
    # cheap-first pairing the reference uses LOCAL_LP for.
    LOCAL_GLOBAL_LP = "local-global-lp"
    # Handshake heavy-edge matching across shards (hem_clusterer.cc; pairs
    # may span shards — dist/hem.py).
    GLOBAL_HEM = "global-hem"
    # HEM pass first, then GLOBAL_LP growing the matched pairs
    # (hem_lp_clusterer.cc).
    GLOBAL_HEM_LP = "global-hem-lp"


class RefinementAlgorithm(enum.Enum):
    """Refiners composable into a pipeline (reference: ``RefinementAlgorithm``)."""

    NOOP = "noop"
    LP = "lp"
    CLP = "clp"  # colored LP
    JET = "jet"
    KWAY_FM = "kway-fm"
    OVERLOAD_BALANCER = "overload-balancer"
    UNDERLOAD_BALANCER = "underload-balancer"
    GREEDY_BALANCER = "greedy-balancer"  # alias used by some presets


class InitialPartitioningMode(enum.Enum):
    SEQUENTIAL = "sequential"


class TieBreakingStrategy(enum.Enum):
    """LP tie-breaking (reference: ``TieBreakingStrategy``, kaminpar.h).

    LIGHTEST is TPU-specific: among equally-rated clusters prefer the one
    with the lowest current weight (then random).  On unweighted geometric
    graphs integer ratings tie constantly and uniform tie-breaking lets a
    few clusters snowball; biasing toward the lighter cluster grows
    rounder, evenly-sized clusters (the size-constrained-LP idea)."""

    UNIFORM = "uniform"
    GEOMETRIC = "geometric"
    LIGHTEST = "lightest"


class ClusterWeightLimit(enum.Enum):
    """Max-cluster-weight formula (reference: coarsening/max_cluster_weights.h)."""

    EPSILON_BLOCK_WEIGHT = "epsilon-block-weight"
    BLOCK_WEIGHT = "block-weight"
    ONE = "one"
    ZERO = "zero"


@dataclass
class LabelPropagationContext:
    """Knobs of the LP engine (reference: ``LabelPropagationCoarseningContext``
    / ``LabelPropagationRefinementContext``, and the CRTP config block at
    ``kaminpar-shm/label_propagation.h:36-74``)."""

    num_iterations: int = 5
    # LP round kernel backend: "xla" (the gather/sort-reduce/segment-sum
    # lowering), "pallas" (the fused gather+rate+commit kernels in
    # ops/pallas_lp.py; off-TPU they run in interpret mode and produce
    # bit-identical results), or "auto" (pallas on TPU backends, xla
    # elsewhere).  One knob serves both the clusterer and the refiner —
    # the same two kernels implement both instantiations.
    lp_kernel: str = "xla"
    # Nodes with degree above this are handled by the dedicated high-degree
    # (edge-parallel) path; mirrors the two-phase threshold of 10k at
    # label_propagation.h:62.
    large_degree_threshold: int = 1_000_000
    max_num_neighbors: int = -1  # -1 = unlimited
    tie_breaking: TieBreakingStrategy = TieBreakingStrategy.UNIFORM
    # Stop sweeping early once fewer than this fraction of nodes moved.
    min_moved_fraction: float = 0.001
    # Cluster isolated nodes together at the end of coarsening LP
    # (reference: label_propagation.h:872-917).
    cluster_isolated_nodes: bool = True
    # Match otherwise-unmergeable singleton clusters through their favored
    # cluster (reference two-hop clustering, label_propagation.h:919-1120).
    cluster_two_hop_nodes: bool = True
    # Fraction of nodes allowed to move per synchronous round — the
    # bulk-synchronous analog of the reference's chunked rounds; < 1 breaks
    # Jacobi-LP swap cycles (see ops/lp.py:_commit_moves).
    active_prob: float = 1.0
    # Accept zero-gain moves with probability 1/2 (the reference LP
    # refiner's tie behavior, lp_refiner.cc:258-260); requires
    # active_prob < 1 to stay oscillation-safe under synchronous commits.
    allow_tie_moves: bool = False
    # Low-degree boost (round-3 A/B, BASELINE_measured.md): synchronous LP
    # propagates labels one hop per sweep, so sparse graphs (grids, roads)
    # converge slower per sweep than dense ones — measured grid256 k=64
    # ratio 1.46 -> 1.20 at 3x sweeps, while 2x sweeps *hurt* dense
    # geometric rgg64k (1.26 -> 1.39).  Levels with avg degree below the
    # threshold get factor x num_iterations.
    low_degree_boost_threshold: float = 8.0
    low_degree_boost_factor: int = 3
    # Weighted-graph clustering mode (graphs with non-uniform edge weights;
    # see lp_clusterer.py): emulate asynchronous incremental growth with a
    # small active fraction and proportionally more sweeps.  Measured on
    # road512 (round 4): coarse-space bisection optimum 2.0x -> 1.07x of
    # the fine-space optimum.  Replaces the low-degree boost on this class.
    weighted_active_prob: float = 0.1
    weighted_sweep_factor: int = 6
    # None = auto-detect from the coarsener's input graph.  The facade pins
    # this to the *user's* graph before partitioning so nested extension
    # pipelines (whose subgraphs carry accumulated weights even for
    # unweighted inputs) inherit the right mode.
    weighted_mode: object = None


@dataclass
class SparsificationContext:
    """Threshold edge sparsification after contraction (reference:
    ``SparsificationClusterCoarseningContext`` + the threshold-sparsifying
    coarsener, sparsification_cluster_coarsener.cc:42-228, ESA'25): keep
    the target_m heaviest coarse edges (ties sampled by a symmetric hash so
    both directions agree), bounding per-level work for worst-case
    linear-time coarsening.  Defaults = reference presets.cc:172-177."""

    enabled: bool = False
    density_target_factor: float = 0.5
    edge_target_factor: float = 0.5
    laziness_factor: float = 4.0


@dataclass
class CoarseningContext:
    """Reference: ``CoarseningContext`` (kaminpar.h) + max_cluster_weights.h."""

    algorithm: ClusteringAlgorithm = ClusteringAlgorithm.LP
    lp: LabelPropagationContext = field(
        default_factory=lambda: LabelPropagationContext(active_prob=0.5)
    )
    # Coarsen until n <= contraction_limit * k (kway) or 2*contraction_limit
    # (deep); reference default C = 2000 (deep_multilevel.cc:170-183).
    contraction_limit: int = 2000
    # Bound per-level shrink: cluster weight additionally capped at
    # max_shrink_factor * average node weight (0 disables).  See
    # cluster_coarsener.coarsen_once for why synchronous LP needs this.
    max_shrink_factor: float = 3.5
    # Stop coarsening when a level shrinks by less than this factor
    # (reference: convergence_threshold).
    convergence_threshold: float = 0.05
    cluster_weight_limit: ClusterWeightLimit = ClusterWeightLimit.EPSILON_BLOCK_WEIGHT
    cluster_weight_multiplier: float = 1.0
    # Overlay clustering (reference: overlay_cluster_coarsener.cc, ESA'25):
    # intersect this many independent LP clusterings; two nodes share an
    # overlay cluster only if every run agrees.  Slower shrink per level,
    # rounder clusters (variance of any single randomized run cancels).
    # <= 1 disables.
    overlay_levels: int = 1
    sparsification: SparsificationContext = field(
        default_factory=SparsificationContext
    )
    # Distributed clusterer selection (dist ClusteringAlgorithm,
    # dkaminpar.h:73-78).
    dist_clustering: DistClusteringAlgorithm = DistClusteringAlgorithm.GLOBAL_LP


@dataclass
class InitialPartitioningContext:
    """Reference: ``InitialPartitioningContext`` — pool of sequential flat
    bipartitioners + 2-way FM (initial_pool_bipartitioner.cc:24)."""

    mode: InitialPartitioningMode = InitialPartitioningMode.SEQUENTIAL
    # Bipartitioning-pool backend (round 9, ISSUE 4): "host" = the
    # sequential NumPy pool + mini-multilevel below (the reference-faithful
    # oracle), "device" = every repetition as a vmapped lane of the JAX pool
    # (ops/bipartition.py; one blocking readback per bisection, per-lane
    # streams from utils/rng.lane_keys), "auto" = device on accelerator
    # backends, host on CPU.  The host pool stays the fallback: a device
    # dispatch failure falls back per bisection instead of aborting.
    # KAMINPAR_TPU_IP_BACKEND overrides.
    ip_backend: str = "auto"
    # Spend the imbalance budget evenly across bisection levels (reference:
    # use_adaptive_epsilon / create_twoway_context, helper.cc:103-130).
    use_adaptive_epsilon: bool = True
    # Number of repetitions of each enabled flat bipartitioner.
    min_num_repetitions: int = 4
    max_num_repetitions: int = 12
    num_seed_iterations: int = 1
    use_adaptive_bipartitioner_selection: bool = True
    enable_bfs_bipartitioner: bool = True
    enable_ggg_bipartitioner: bool = True
    enable_random_bipartitioner: bool = True
    # 2-way FM refinement of each bipartition.
    fm_num_iterations: int = 5
    fm_alpha: float = 1.0  # adaptive stopping alpha (Osipov/Sanders)
    # Sequential mini-multilevel inside each bisection (reference:
    # initial_multilevel_bipartitioner.cc:67-74, C=20).
    coarsening_contraction_limit: int = 20
    # Shrink factor below which IP coarsening is considered converged
    # (reference: InitialCoarseningContext::convergence_threshold = 0.05).
    coarsening_convergence_threshold: float = 0.05
    # Extension splits into >= 4 parts on subgraphs at least this large use
    # a nested (device) deep pipeline instead of chained host bisections —
    # measured stronger on dense geometric graphs (extend_partition).
    nested_extension_n: int = 4096
    # Independent nested attempts per extension block; best cut wins.
    # Round-2 measured on rgg64k k=64: reps=2 cuts seed variance ~4x
    # (spread 8.9k -> 1.9k) at unchanged mean; round-3 on grid256 k=64 it
    # moves the default-tier mean 1.38 -> 1.24 over seeds {1,2,3}
    # (QUALITY_NOTES.md) — bad extension splits were the variance source.
    # Cost: ~+20% wall on mesh configs.  Default 2 since round 3.
    nested_extension_reps: int = 2
    # Up to this finest-graph size, also run the flat pool on the finest
    # graph and keep the better of {mini-ML, flat} — measured divergence
    # from the reference (which always uses ML): on expander-like coarse
    # graphs (RMAT) flat pool+FM beats the projected ML partition, while
    # on geometric/mesh graphs ML wins; best-of is cheap at this size.
    flat_pool_fallback_n: int = 2048
    # Device-side extension (round 5, VERDICT r4 missing #4): on graphs at
    # least device_extension_n nodes, extension runs ONE restricted nested
    # multilevel batched over all blocks (partitioning/extension.py) instead
    # of host per-block subgraph pipelines.  The host only sees the nested
    # coarsest graph (~device_extension_cpb coarse nodes per new block).
    device_extension: bool = False
    device_extension_n: int = 1 << 15
    device_extension_cpb: int = 320
    # Independent device-extension attempts, best full-graph cut wins
    # (extension variance was the rgg64k plateau driver; same rationale as
    # nested_extension_reps on the host path).
    device_extension_reps: int = 1


@dataclass
class JetContext:
    """Reference: ``JetRefinementContext`` (refinement/jet/jet_refiner.cc)."""

    # Number of full JET invocations chained per refinement step (reference:
    # create_jet_context(num_rounds), presets.cc "jet"/"4xjet").
    num_rounds: int = 1
    num_iterations: int = 12
    num_fruitless_iterations: int = 12
    fruitless_threshold: float = 0.999
    # Negative-gain filter temperatures on fine/coarse levels
    # (reference: jet_refiner.cc fine/coarse temperature schedule).
    initial_gain_temp_on_fine_level: float = 0.25
    final_gain_temp_on_fine_level: float = 0.25
    initial_gain_temp_on_coarse_level: float = 0.75
    final_gain_temp_on_coarse_level: float = 0.75


@dataclass
class BalancerContext:
    max_num_rounds: int = 8


@dataclass
class ColoredLPContext:
    """Colored LP refiner parameters (reference: ``ColoredLPRefinementContext``,
    clp_refiner.cc)."""

    num_iterations: int = 2
    # Zero-gain moves are oscillation-safe inside a color class (independent
    # set); they restore async-LP boundary diffusion.
    allow_tie_moves: bool = True
    # Same backend switch as LabelPropagationContext.lp_kernel — the CLP
    # superstep is the same fused round with a color-class mask.
    lp_kernel: str = "xla"


@dataclass
class FMContext:
    """k-way FM refiner parameters (reference: ``KwayFMRefinementContext``,
    presets.cc:348-365)."""

    num_iterations: int = 10
    alpha: float = 1.0  # adaptive stopping (Osipov/Sanders)
    num_fruitless_moves: int = 100
    abortion_threshold: float = 0.999
    # Border seeds consumed per localized search region (presets.cc:350).
    num_seed_nodes: int = 10
    # Deterministic per-pass work budget: a pass stops (after finishing its
    # current region) once the summed degree of popped nodes exceeds
    # factor * n.  Bounds the *sequential host* pass on dense graphs
    # (rgg64k: deg ~50 makes full-border passes ~30x a road pass for no
    # measured cut gain); the reference affords full passes because its
    # searches run on all cores.  <= 0 disables.
    pass_work_budget_factor: float = 32.0
    # TPU divergence: FM runs as a sequential host pass; JET is the at-scale
    # device refiner (see fm_refiner.py module docstring).  Below
    # ``dense_nk_threshold`` connection entries the pass uses a dense (n, k)
    # matrix (the reference's dense_gain_cache.h analog); above it, a lazily
    # materialized border-row table (sparse_gain_cache.h role) whose memory
    # scales with the border, so there is no n*k gate anymore (VERDICT r3
    # next #6).  ``max_n`` bounds the sequential pass wall-time only.
    max_n: int = 1 << 23
    dense_nk_threshold: int = 1 << 26


class MoveExecutionStrategy(enum.Enum):
    """Distributed LP move commitment (reference:
    LabelPropagationMoveExecutionStrategy, dkaminpar.h:116-120).
    LOCAL_MOVES is the bulk-synchronous analog of the reference's eager
    PE-local application: proposals ignore block caps and departures are
    credited to their block's capacity before arrivals are admitted
    (best-gain-first), so swaps between at-cap blocks stay reachable."""

    PROBABILISTIC = "probabilistic"
    BEST_MOVES = "best-moves"
    LOCAL_MOVES = "local-moves"


@dataclass
class RefinementContext:
    """Pipeline of refiners, run in order on every uncoarsening level
    (reference: MultiRefiner, factories.cc:97-147)."""

    dist_move_execution: MoveExecutionStrategy = MoveExecutionStrategy.PROBABILISTIC
    # Sub-rounds over disjoint hash-chunks of the nodes per dist LP round
    # (reference: dist lp_refiner.cc processes 8 chunks per round to bound
    # move staleness; commits happen between chunks).
    dist_num_chunks: int = 8
    algorithms: tuple = (
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.LP,
    )
    # Strict-improvement LP (measured: bulk-synchronous zero-gain "tie"
    # moves *hurt* — simultaneous tie movers interact; async diffusion has
    # no safe sync analog here.  JET plays that role instead.)
    lp: LabelPropagationContext = field(
        default_factory=lambda: LabelPropagationContext(num_iterations=5)
    )
    jet: JetContext = field(default_factory=JetContext)
    balancer: BalancerContext = field(default_factory=BalancerContext)
    fm: FMContext = field(default_factory=FMContext)
    clp: ColoredLPContext = field(default_factory=ColoredLPContext)


@dataclass
class PartitionContext:
    """Target partition parameters (reference: ``PartitionContext``), filled in
    by ``setup`` once graph + k are known (kaminpar.cc:315-331)."""

    k: int = 2
    epsilon: float = 0.03
    # Minimum block-weight imbalance; 0 disables minimum weights (reference:
    # KaMinPar::set_uniform_min_block_weights, kaminpar.cc:266-269).
    min_epsilon: float = 0.0
    # Filled by setup():
    total_node_weight: int = 0
    max_block_weights: Optional[object] = None  # np.ndarray[k], set by setup()
    min_block_weights: Optional[object] = None  # np.ndarray[k] or None

    def setup(
        self, total_node_weight: int, k: int, epsilon: float, min_epsilon: float = 0.0
    ) -> None:
        import math

        import numpy as np

        self.k = int(k)
        self.epsilon = float(epsilon)
        self.min_epsilon = float(min_epsilon)
        self.total_node_weight = int(total_node_weight)
        perfect = (total_node_weight + k - 1) // k
        max_bw = int((1.0 + epsilon) * perfect)
        # Strict balance for unweighted graphs requires max >= perfect + max
        # node weight; the facade adjusts for node weights (kaminpar.cc).
        self.max_block_weights = np.full(k, max(max_bw, perfect + 1), dtype=np.int64)
        if min_epsilon > 0.0:
            # min_bw = ceil((1 - min_eps) * perfect) (context.cc:72-81),
            # clamped so k * min_bw <= W stays satisfiable (perfect is
            # already rounded up, so the raw formula can over-demand).
            min_bw = int(math.ceil((1.0 - min_epsilon) * perfect))
            min_bw = min(min_bw, total_node_weight // k)
            self.min_block_weights = np.full(k, min_bw, dtype=np.int64)
        else:
            self.min_block_weights = None


@dataclass
class ParallelContext:
    """TPU execution parameters (replaces the reference's thread counts)."""

    # Shape of the device mesh for the distributed tier; None = single chip.
    mesh_shape: Optional[tuple] = None
    mesh_axis_names: tuple = ("nodes",)
    # Persistent XLA compilation cache.  Multilevel runs hit the bounded
    # geometric shape-bucket ladder (graph/csr.py), so caching the compiled
    # kernels on disk makes every run after the first start warm — on a
    # tunneled TPU that is ~35-48 s saved per kernel shape (TPU_NOTES.md).
    # The facade/engine owns these through its EngineRuntime (activated
    # per run); the env-var defaults (KAMINPAR_TPU_CACHE_DIR /
    # KAMINPAR_TPU_NO_CACHE, applied at import in kaminpar_tpu/__init__.py)
    # act as the fallback.
    persistent_compilation_cache: bool = True
    compilation_cache_dir: str = ""  # "" = env var or ~/.cache default
    # Degree-bucketed layout construction backend (graph/csr.py):
    # "host" = numpy over pulled CSR arrays (zero-copy on the CPU backend,
    # a full-graph device->host round trip per hierarchy level through an
    # accelerator tunnel); "device" = jitted gathers fed by the 12-int
    # degree histogram riding each contraction level's single batched
    # readback (no bulk transfer, a few small extra kernel shapes);
    # "auto" = device on accelerator backends, host on CPU.
    device_layout_build: str = "auto"
    # Profiling aid (utils/timer.py): make sync-eligible timer scopes block
    # on their sentinel so phase wall-clock measures compute, not dispatch.
    # Off by default — it serializes the async pipeline it measures.
    sync_timers: bool = False
    # Fleet placement (round 18, serve/fleet.py): index into jax.devices()
    # this engine's dispatches default to.  None = jax's own default (device
    # 0).  The EngineRuntime activation wraps jax.default_device around the
    # owning engine's pipeline runs, so N single-device engine replicas in
    # one process land on N distinct mesh devices (arrays stay uncommitted —
    # placement steers dispatch, it never forbids a transfer).  On the CPU
    # backend the "devices" are the forced virtual host devices (the same
    # dryrun substrate the shard_ab bench uses), which SERIALIZE — see
    # TPU_NOTES round 18 for what a CPU fleet number does and does not claim.
    placement_device: Optional[int] = None


# ---------------------------------------------------------------------------
# Per-engine runtime ownership (ISSUE 6 unlocking refactor).
#
# Until round 11 the compilation-cache / layout-build / sync-timer settings
# were applied as *first-wins process globals* (`_configure_once`): the first
# facade or engine instance won, and a second instance with a conflicting
# context got a RuntimeWarning and silently inherited the first one's
# behavior.  That made heterogeneous warm pools (a small-graph lane engine
# next to a big-graph engine in one process) impossible.  `EngineRuntime`
# replaces the records with *ownership*: every facade/engine owns a runtime
# derived from its own ParallelContext and activates it (thread-locally)
# around its pipeline runs, so two engines with different configs coexist
# and each one's dispatches see its own settings.
# ---------------------------------------------------------------------------

import threading as _threading
from contextlib import contextmanager as _contextmanager

_tls_runtime = _threading.local()

# Last cache settings actually pushed into the live jax config (the jax
# compilation cache is genuinely process-global, so activation switches it
# on demand and memoizes to avoid redundant config churn; entries from
# several engines' cache dirs coexist on disk).
_applied_cache_settings: list = [None]
# The *process default* cache settings — what compiles outside any
# activation should see.  Set by configure_compilation_cache (last wins)
# or lazily captured from the live jax config (the import-time setup in
# __init__.py uses raw jax.config updates) when the first activation
# starts with no activation live anywhere in the process.  Every
# stack-emptying activation exit restores this record, never a snapshot
# of whatever another engine's thread applied mid-run.
_process_default_cache: list = [None]
_active_activations: list = [0]
_cache_lock = _threading.Lock()


def current_runtime() -> "Optional[EngineRuntime]":
    """The :class:`EngineRuntime` active on this thread (innermost
    activation), or None outside any activation."""
    stack = getattr(_tls_runtime, "stack", None)
    return stack[-1] if stack else None


def _resolve_cache_settings(parallel: "ParallelContext") -> tuple:
    import os

    if not parallel.persistent_compilation_cache:
        return (False, None)
    cache_dir = (
        parallel.compilation_cache_dir
        or os.environ.get("KAMINPAR_TPU_CACHE_DIR")
        or os.path.join(os.path.expanduser("~"), ".cache", "kaminpar_tpu", "xla")
    )
    return (True, cache_dir)


def _apply_cache_settings(settings: tuple) -> None:
    """Push cache settings into the live jax config (memoized; last wins).

    Reference for why AOT executable caching stays off: the round-3 CPU
    serializer crashes (see kaminpar_tpu/__init__.py)."""
    import os

    if os.environ.get("KAMINPAR_TPU_NO_CACHE", "0") == "1":
        return  # env kill switch wins (benchmarks measuring cold compiles)
    with _cache_lock:
        if _applied_cache_settings[0] == settings:
            return
        import jax

        enabled, cache_dir = settings
        try:
            if not enabled:
                jax.config.update("jax_compilation_cache_dir", None)
            else:
                os.makedirs(cache_dir, exist_ok=True)
                # Tuning knobs are optional — their absence must not disable
                # the cache itself.
                for knob, val in (
                    ("jax_persistent_cache_min_compile_time_secs", 0.5),
                    ("jax_persistent_cache_min_entry_size_bytes", 0),
                ):
                    try:
                        jax.config.update(knob, val)
                    except Exception:
                        pass
                # The AOT-executable guard is load-bearing (CPU serializer
                # crashes, see kaminpar_tpu/__init__.py) and must be live
                # BEFORE the cache dir: if it is missing, the except below
                # keeps the cache off.
                jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
                jax.config.update("jax_compilation_cache_dir", cache_dir)
            _applied_cache_settings[0] = settings
        except Exception:  # pragma: no cover — optimization only
            pass


@dataclass(frozen=True)
class EngineRuntime:
    """Per-engine ownership of compilation-cache / layout / sync-timer
    settings (the knobs that used to be first-wins process globals).

    Built from a :class:`ParallelContext` by the facade or engine that owns
    the pipeline, and *activated* (a thread-local stack, so nested runs and
    concurrent engine dispatcher threads stay independent) around every
    pipeline run:

    - **compilation cache**: the jax cache-dir config is switched to this
      runtime's settings on activation (the jax config is process-global,
      so switching is on-demand and memoized; two engines' cache dirs
      coexist on disk).  The switch happens at activation *entry* only —
      compiles triggered while another engine's thread activates mid-run
      land in the most recently applied dir.  That costs cache locality,
      never correctness (entries are keyed by computation); layout and
      sync-timer ownership below are thread-local and unaffected.
    - **layout build**: ``graph.csr.resolve_layout_build_mode`` consults the
      active runtime before the process default, so graphs built inside an
      activation use this engine's builder even without a per-graph pin.
    - **sync timers**: ``scoped_timer(..., sync=True)`` blocks per the
      active runtime's flag, not a global switch.
    """

    cache_enabled: bool = True
    cache_dir: Optional[str] = None
    layout_build: str = "auto"
    sync_timers: bool = False
    # Fleet placement (round 18): jax.devices() index the activation pins as
    # jax.default_device for this thread; None = backend default.
    device_index: Optional[int] = None

    @classmethod
    def from_parallel(cls, parallel: "ParallelContext") -> "EngineRuntime":
        enabled, cache_dir = _resolve_cache_settings(parallel)
        return cls(
            cache_enabled=enabled,
            cache_dir=cache_dir,
            layout_build=parallel.device_layout_build,
            sync_timers=bool(parallel.sync_timers),
            device_index=parallel.placement_device,
        )

    @_contextmanager
    def activate(self):
        """Context manager making this runtime current on this thread and
        applying its compilation-cache settings to the live jax config.
        When this thread's activation stack empties, the recorded *process
        default* (:func:`configure_compilation_cache`, or the pre-activation
        live config captured lazily) is restored — so a facade run never
        clobbers the default for compiles outside any activation, even when
        engine activations overlap across threads (the mid-run dir switches
        such overlap causes cost cache locality, never correctness)."""
        stack = getattr(_tls_runtime, "stack", None)
        if stack is None:
            stack = _tls_runtime.stack = []
        with _cache_lock:
            if _active_activations[0] == 0 and _process_default_cache[0] is None:
                # First activation process-wide with no configured default:
                # capture the live config (e.g. the import-time raw
                # jax.config setup in __init__.py) as the default to
                # restore.
                try:
                    import jax

                    raw = jax.config.jax_compilation_cache_dir
                    _process_default_cache[0] = (raw is not None, raw)
                except Exception:  # pragma: no cover — optimization only
                    pass
            _active_activations[0] += 1
        _apply_cache_settings((self.cache_enabled, self.cache_dir))
        stack.append(self)
        device_ctx = None
        if self.device_index is not None:
            # Fleet placement: pin jax's (thread-local) default device so
            # this runtime's dispatches land on its replica's mesh device.
            # Arrays stay uncommitted — a graph buffer created under another
            # replica's activation is transferred, never rejected — so
            # replicas may legally share input graphs.
            try:
                import jax

                devs = jax.devices()
                device_ctx = jax.default_device(
                    devs[self.device_index % len(devs)]
                )
                device_ctx.__enter__()
            except Exception:  # pragma: no cover — placement is a locality
                device_ctx = None  # optimization, never a correctness gate
        try:
            yield self
        finally:
            if device_ctx is not None:
                device_ctx.__exit__(None, None, None)
            stack.pop()
            with _cache_lock:
                _active_activations[0] -= 1
                default = _process_default_cache[0]
            prev = current_runtime()
            if prev is not None:
                _apply_cache_settings((prev.cache_enabled, prev.cache_dir))
            elif default is not None:
                _apply_cache_settings(default)


def propagate_runtime(fn):
    """Wrap a thread-pool worker so the *submitting* thread's active
    :class:`EngineRuntime` is re-activated inside the worker.

    Thread-local activation does not cross pool threads — the PR 6 escape
    class: a nested-extension worker resolving layout/sync settings falls
    through to the process default even while its owning engine's
    activation is live on the dispatcher thread.  Every pool submission in
    the device-disciplined tier wraps its worker with this (the per-graph
    ``_layout_mode`` pins remain as belt-and-braces for graphs that outlive
    the activation).  No-op (returns ``fn`` unchanged) outside any
    activation."""
    rt = current_runtime()
    if rt is None:
        return fn

    def _wrapped(*args, **kwargs):
        with rt.activate():
            return fn(*args, **kwargs)

    return _wrapped


def reset_global_configuration() -> None:
    """Forget the memoized cache application and the recorded process
    default so the next activation re-applies and re-captures
    unconditionally (tests and long-lived REPLs).  Kept from the
    first-wins era; there are no conflict records anymore."""
    with _cache_lock:
        _applied_cache_settings[0] = None
        _process_default_cache[0] = None


def configure_compilation_cache(parallel: ParallelContext) -> None:
    """Apply the context's persistent-cache settings to the live jax config
    as the process default (last-wins, no conflict warning) — the setting
    activations restore when their stack empties.  Facades and engines own
    an :class:`EngineRuntime` instead and activate it per run; this entry
    point remains for tools and scripts that configure the process once up
    front."""
    settings = _resolve_cache_settings(parallel)
    with _cache_lock:
        _process_default_cache[0] = settings
    _apply_cache_settings(settings)


@dataclass
class ServeContext:
    """Knobs of the partition-serving runtime (:mod:`kaminpar_tpu.serve`).

    A :class:`~kaminpar_tpu.serve.PartitionEngine` owns one long-lived device
    context: it precompiles the executable set over the ``warm_ladder`` x
    ``warm_ks`` grid at startup, keeps workspaces device-resident between
    requests, and serves a bounded async queue with admission control,
    deadlines, and micro-batching of same-shape-cell requests."""

    # Node-count rungs to warm at startup (powers of two; each rung warms
    # its whole padded bucket chain by running one synthetic partition).
    warm_ladder: tuple = (256, 1024)
    # k values to warm per rung.
    warm_ks: tuple = (8,)
    # Edge factor of the synthetic (RMAT) warmup graphs.
    warm_edge_factor: int = 8
    # Max requests fused into one micro-batch (same (n-bucket, m-bucket, k)
    # shape cell only; see serve/batching.py).
    max_batch: int = 8
    # Admission bound of the request queue; submits beyond it are rejected
    # with a retry-after estimate (backpressure) instead of queueing without
    # limit.
    queue_bound: int = 64
    # After the first request of a batch arrives, wait up to this long for
    # more same-cell requests before dispatching the batch.
    batch_window_ms: float = 2.0
    # Default per-request deadline; 0 disables (requests wait forever).
    default_deadline_ms: float = 0.0
    # Graceful-shutdown budget: how long shutdown(drain=True) waits for the
    # queue to empty before giving up on the dispatcher thread.
    drain_timeout_s: float = 60.0
    # Lane-stacked batch execution (round 11, serve/lanestack.py): run a
    # whole same-cell micro-batch through the multilevel pipeline as ONE
    # vmapped program instead of once per graph.  "auto" lane-stacks
    # eligible batches of >= 2 requests; "on" additionally stacks
    # single-request batches (and makes fallbacks warn); "off" keeps the
    # per-graph loop.  KAMINPAR_TPU_LANE_STACK overrides.
    lane_stack: str = "auto"
    # Lane counts to warm the lane-stacked pipeline at per (rung, k) cell
    # during startup warmup (kind="lanestack" warmup-report rows); empty
    # disables the pass (the per-graph warmup stays as is).
    warm_lanes: tuple = ()
    # HBM admission preflight (ISSUE 12; telemetry/capacity.py): "auto"
    # rejects a request whose predicted watermark exceeds the engine's
    # ceiling when a ceiling is known (explicit override below, measured
    # allocator limit, or the device-kind table — CPU without allocator
    # stats has none, so "auto" passes everything there); "off" disables.
    capacity_preflight: str = "auto"
    # Explicit admission ceiling in bytes; 0 = derive (allocator bytes_limit
    # when the backend exposes one, else the per-device-kind HBM table at
    # the planner's headroom).  Tests pin small values to force rejection.
    capacity_ceiling_bytes: int = 0
    # Crash-safe serve journal (round 19, serve/journal.py): append-only
    # JSONL path ("" = off; env KPTPU_SERVE_JOURNAL overrides).  Every
    # admitted request is journaled at admit (graph payload + params) and
    # again at resolution; a restarted engine replays unresolved entries
    # idempotently — restart mid-burst loses zero accepted requests — and
    # restores the warm state (warmup report, warm cells, breaker trips,
    # EMA seed) recorded alongside, so the replacement starts warm with a
    # zero warmup compile-event delta.
    journal_path: str = ""
    # fsync the journal every N appended records (durability vs latency;
    # the un-fsynced suffix is the crash-loss window — TPU_NOTES r19 on
    # what batched fsync does and does not guarantee).  Resolutions and
    # the warm-state record force an fsync regardless.
    journal_fsync_every: int = 8
    # -- SLO objectives (round 20, telemetry/slo.py) ------------------------
    # Declared service objectives; ALL default off (0.0), which disables
    # burn-rate accounting entirely.  When any is armed the engine keeps
    # rolling multi-window error budgets (slo_windows_s), exposes them in
    # stats()["slo"] + kaminpar_slo_* Prometheus families, and exports a
    # dimensionless pressure signal max(0, worst_burn - 1) that the fleet
    # steering score and the autoscaler consume.  Pressure is a control
    # input only — it never reaches the partitioning math, so partitions
    # stay bit-identical with SLOs armed or off (asserted in tests).
    #
    # Per-quality-tier latency targets in milliseconds (queue wait +
    # execute, i.e. the caller-observed service path of a completed
    # request); a completed request over its tier's target spends latency
    # error budget (budget = 1 - slo_availability, or 1% when no
    # availability objective is set).
    slo_strong_ms: float = 0.0
    slo_fast_ms: float = 0.0
    # Availability target as a fraction (e.g. 0.999): failed/expired
    # requests spend the (1 - target) error budget.
    slo_availability: float = 0.0
    # Tolerated capacity-reject rate as a fraction of submissions (e.g.
    # 0.01): typed CapacityError rejections beyond it burn budget.
    slo_capacity_reject_rate: float = 0.0
    # Rolling evaluation windows in seconds (fast burn / slow burn pair).
    slo_windows_s: tuple = (60.0, 600.0)


@dataclass
class FleetContext:
    """Knobs of the mesh-replicated serve fleet (round 18,
    :mod:`kaminpar_tpu.serve.fleet`).

    A :class:`~kaminpar_tpu.serve.fleet.PartitionFleet` owns N
    :class:`~kaminpar_tpu.serve.PartitionEngine` replicas — one per mesh
    device by default — and steers each request to a replica with an
    SLO-aware score over the replicas' live serving signals (queue drain
    estimate, p99 execute, open breakers, capacity-preflight verdict)
    instead of a single EMA.  Same-cell load fans in per replica up to the
    engine's ``max_batch`` (the lane axis) before spilling to the next
    replica (the device axis) — the lane x device 2D plane."""

    # Replica count; 0 = one per visible jax device (the whole local mesh).
    replicas: int = 0
    # Graph-id-sticky routing: a request carrying ``graph_id`` keeps landing
    # on the replica that first served that id while it stays healthy, so a
    # tenant's warm graph state (and, once incremental repartitioning
    # lands, its resident delta-graph) stays on one device.
    sticky_routing: bool = True
    # Steering-score weights: queue term (drain-time estimate of the
    # replica's queued work) and tail-latency term (p99 execute seconds).
    steer_queue_weight: float = 1.0
    steer_p99_weight: float = 1.0
    # Score bonus (in service-time units) for joining a replica's *forming*
    # same-cell batch (0 < same-cell depth < max_batch): fills the lane
    # axis to max_batch before spilling to the next device, maximizing
    # stacked occupancy.  >= (max_batch-1)/max_batch keeps a forming batch
    # preferred over an idle sibling until it is full.
    batch_join_bonus: float = 1.0
    # Floor for the per-request service-time estimate used by the steering
    # score and the fleet drain estimate before any EMA exists.
    steer_service_floor_s: float = 0.05
    # Cross-replica requeue budget per request: how many times a request
    # force-resolved by a draining/hung replica (typed EngineStoppedError /
    # WorkerHung / watchdog ExecuteFault) is resubmitted elsewhere before
    # the typed error surfaces to the caller.
    max_resteers: int = 2
    # Drain a replica automatically when its watchdog fires or when at
    # least ``auto_drain_open_cells`` of its cell breakers latch open
    # (0 disables auto-drain; ``drain_replica`` stays available).
    auto_drain: bool = True
    auto_drain_open_cells: int = 2
    # Fleet-scoped replica breaker: a drained replica re-admits one probe
    # request after this cooldown (restart + half-open, like every other
    # ladder rung).
    replica_cooldown_s: float = 30.0
    # Warm-cache inheritance: replica N+1 shares the fleet's persistent
    # compilation cache dir and imports the warmup report of the first
    # warmed replica, skipping every cell already traced (inherited vs
    # locally-compiled counts ride warmup_report and Prometheus).
    inherit_warm_cache: bool = True
    # Bounded per-replica drain budget used by drain_replica/shutdown.
    drain_timeout_s: float = 30.0
    # -- elastic scaling (round 19, ISSUE 15) -------------------------------
    # ``PartitionFleet.scale_to(N)`` adds/removes replicas under live
    # traffic: scale-up revives retired slots (warm state carries over)
    # before spawning fresh replicas (which inherit the fleet's warm
    # cache); scale-down retires the highest-index active replicas through
    # the PR 14 drain/resteer machinery — zero lost/duplicated resolutions
    # (asserted in tests/test_elastic.py).
    #
    # ``autoscale`` drives scale_to from the live steer signals: the mean
    # per-replica queue-drain estimate (depth x unamortized EMA /
    # max_batch) crossing the high watermark for ``autoscale_hysteresis``
    # consecutive health sweeps scales up one replica; staying under the
    # low watermark scales down one (never past the min/max bounds).
    autoscale: bool = False
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 8
    autoscale_high_s: float = 1.0
    autoscale_low_s: float = 0.05
    autoscale_hysteresis: int = 3
    # -- SLO pressure feedback (round 20, telemetry/slo.py) -----------------
    # Weight of a replica's SLO burn pressure in the steering score, in
    # service-time units per unit of excess burn: a replica burning its
    # error budget looks "slower" to the router and sheds new load to
    # healthier siblings.  Inert (term is 0) unless the engines' ServeContext
    # arms SLO objectives.
    steer_slo_weight: float = 1.0
    # Seconds added to the autoscaler's mean drain estimate per unit of
    # mean excess burn across active replicas: sustained budget burn pulls
    # the fleet toward the high watermark (scale-up) even when raw queue
    # depth alone would not cross it.  Inert unless SLOs are armed.
    autoscale_slo_boost: float = 1.0
    # Replace (not just drain) a replica the health sweep takes out of
    # rotation — a fresh replica inheriting the fleet's warm state spawns
    # immediately so capacity does not dip for the drain cooldown.
    # Implied by ``autoscale``.
    replace_drained: bool = False


@dataclass
class ResilienceContext:
    """Knobs of the unified resilience layer (round 17,
    :mod:`kaminpar_tpu.resilience`): fault injection, circuit breakers,
    the execution watchdog.  All defaults are production-safe no-ops —
    injection disarmed, watchdog off, breakers at the documented
    threshold/cooldown."""

    # Fault plan armed at engine start (resilience/faults.py syntax:
    # "point[@site]:error[:key=val ...]", comma-separated).  Empty =
    # disarmed.  Env KPTPU_FAULTS (+ KPTPU_FAULTS_SEED) arms globally and
    # reaches child processes; an armed plan makes chaos runs replayable
    # because injection decisions are seed-keyed, not drawn from any RNG
    # stream.
    fault_plan: str = ""
    fault_seed: int = 0
    # Consecutive failures that open a (path, cell) breaker, and how long
    # it stays open before the half-open probe re-admits one dispatch.
    # These govern the ENGINE's registry; pipeline sites outside any
    # engine use the process-global registry (env-tunable via
    # KPTPU_BREAKER_THRESHOLD / KPTPU_BREAKER_COOLDOWN_S).
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 30.0
    # Execution-watchdog deadlines (resilience/watchdog.py); 0 disables.
    # A serve batch overrunning execute_timeout_s has its futures
    # force-resolved with a typed ExecuteFault and its cell breaker
    # tripped — the dispatch itself is abandoned, not cancelled (threads
    # are not interruptible; the idempotent future discards late
    # results).
    execute_timeout_s: float = 0.0
    compile_timeout_s: float = 0.0
    # JSONL sidecar for watchdog dossiers ("" = in-memory only; the last
    # 16 ride engine.stats()).
    dossier_path: str = ""
    # Preemption-tolerant execution (round 19, resilience/checkpoint.py):
    # directory for deep-pipeline level-boundary checkpoints ("" =
    # disarmed; env KPTPU_CHECKPOINT arms globally and reaches child
    # processes).  At every coarsening/uncoarsening level boundary the
    # resumable state — level-stack CSR arrays (pulled through ONE
    # counted pull batch under the ``checkpoint_write`` phase), the
    # current partition, the RNG chain position (seed + draw counter),
    # and a context fingerprint — is written with an atomic rename;
    # ``KaMinPar.compute_partition(resume=...)`` / ``tools resume``
    # validates the fingerprint and continues BIT-IDENTICAL to the
    # uninterrupted run (asserted in tests/test_checkpoint.py).
    checkpoint_dir: str = ""
    # Write a checkpoint every N level boundaries (>= 1).
    checkpoint_every_levels: int = 1
    # Keep every boundary's checkpoint file instead of only the latest —
    # the kill-anywhere test matrix resumes from each of them.
    checkpoint_keep_all: bool = False


@dataclass
class GraphCompressionContext:
    """Reference: ``GraphCompressionContext`` (kaminpar.h) — whether the
    input graph is stored compressed (graph/compressed.py, the TeraPart
    analog)."""

    enabled: bool = False
    # Device-decode routing of the compressed stream (ISSUE 10 tentpole;
    # graph/device_compressed.py):
    # - "off": the storage tier only — the DEEP pipeline decompresses the
    #   finest CSR on host before device work (the pre-round-14 behavior).
    # - "finest": the finest level runs directly off the device-resident
    #   compressed stream — clustering + contraction + the final LP
    #   refinement pass decode in-kernel, and the finest re-materialization
    #   at uncoarsening is a device decode kernel.  Bit-identical to the
    #   dense path (asserted); warns + falls back dense outside the
    #   envelope (64-bit build, HEM clustering, v-cycle communities).
    # - "auto": like "finest" but falls back silently.
    # KAMINPAR_TPU_DEVICE_DECODE overrides.
    # The dist tier consumes the SAME knob (round 15,
    # dist/device_compressed.py): under it the finest dist level's
    # adjacency stays resident as per-shard gap streams and the LP/
    # contraction kernels decode in-kernel inside shard_map (envelope:
    # 32-bit + GLOBAL_LP dist clustering; dense staging fallback).
    device_decode: str = "off"


@dataclass
class DebugContext:
    """Reference: the debug dump options consumed by
    kaminpar-shm/partitioning/debug.cc."""

    save_hierarchy: bool = False
    validate_graph: bool = False
    graph_name: str = ""
    dump_dir: str = "."
    dump_graph_hierarchy: bool = False
    dump_partition_hierarchy: bool = False


@dataclass
class Context:
    """Root of the config tree (reference: ``Context``, kaminpar.h:610-622)."""

    preset_name: str = "default"
    mode: PartitioningMode = PartitioningMode.KWAY
    partition: PartitionContext = field(default_factory=PartitionContext)
    coarsening: CoarseningContext = field(default_factory=CoarseningContext)
    initial_partitioning: InitialPartitioningContext = field(
        default_factory=InitialPartitioningContext
    )
    refinement: RefinementContext = field(default_factory=RefinementContext)
    parallel: ParallelContext = field(default_factory=ParallelContext)
    compression: GraphCompressionContext = field(
        default_factory=GraphCompressionContext
    )
    serve: ServeContext = field(default_factory=ServeContext)
    fleet: FleetContext = field(default_factory=FleetContext)
    resilience: ResilienceContext = field(default_factory=ResilienceContext)
    debug: DebugContext = field(default_factory=DebugContext)
    seed: int = 0
    # v-cycle mode: intermediate k values partitioned before the final k
    # (reference: PartitioningContext::vcycles, vcycle_deep_multilevel.cc).
    vcycles: tuple = ()
    # Forbid refinement moves across the previous cycle's blocks
    # (reference: restrict_vcycle_refinement).
    restrict_vcycle_refinement: bool = False
    # int32 by default; int64 mirrors the reference's 64-bit ID/weight build
    # switches (CMakeLists.txt:71-79).
    use_64bit_ids: bool = False

    def to_dict(self) -> dict:
        def conv(obj):
            if dataclasses.is_dataclass(obj):
                return {f.name: conv(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
            if isinstance(obj, enum.Enum):
                return obj.value
            if isinstance(obj, tuple):
                return [conv(x) for x in obj]
            if hasattr(obj, "tolist"):
                return obj.tolist()
            return obj

        return conv(self)

    def dump(self) -> str:
        """Round-trippable config dump (reference: ``--dump-config``,
        apps/KaMinPar.cc:107)."""
        return json.dumps(self.to_dict(), indent=2)
