"""The ``KaMinPar`` facade — public entry point.

Mirrors the reference facade (``include/kaminpar-shm/kaminpar.h:857-1050``,
``compute_partition`` at kaminpar-shm/kaminpar.cc:295-461): owns a graph and a
:class:`Context`, configures k and the block-weight constraints (epsilon /
absolute), runs preprocessing, dispatches the partitioner chosen by the
context, and reports the parseable ``RESULT`` line.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Union

import numpy as np

from .context import Context, PartitioningMode
from .factories import create_partitioner
from .graph import metrics
from .graph.csr import CSRGraph
from .graph.partitioned import PartitionedGraph
from .presets import create_context_by_preset_name
from .utils import Logger, OutputLevel, RandomState, Timer, log_result_line


class KaMinPar:
    """Usage::

        import kaminpar_tpu as kp
        solver = kp.KaMinPar()               # default preset
        solver.set_graph(graph)              # a kaminpar_tpu CSRGraph
        partition = solver.compute_partition(k=64, epsilon=0.03)
    """

    def __init__(self, ctx: Union[Context, str, None] = None, engine=None):
        if ctx is None:
            ctx = create_context_by_preset_name("default")
        elif isinstance(ctx, str):
            ctx = create_context_by_preset_name(ctx)
        self.ctx = ctx
        # Optional warm serving engine (serve/engine.py): compute_partition
        # delegates to it instead of running the cold in-process pipeline.
        self._engine = engine
        # This facade OWNS its runtime settings (compilation cache, layout
        # build, sync timers) instead of racing other instances for
        # first-wins process globals: the runtime is activated thread-locally
        # around every compute_partition, so two facades/engines with
        # conflicting configs coexist in one process (ISSUE 6).
        from .context import EngineRuntime

        self.runtime = EngineRuntime.from_parallel(ctx.parallel)
        self.graph: Optional[CSRGraph] = None
        self.compressed_graph: Optional[object] = None
        self._last: Optional[PartitionedGraph] = None
        self._auto_weighted_pin = False

    # -- graph input -------------------------------------------------------

    def set_graph(self, graph) -> None:
        """Accepts a CSRGraph or a CompressedGraph (reference: the facade's
        Graph variant over CSR/compressed, kaminpar.h).  With
        ``ctx.compression.enabled`` (terapart presets) a CSR input is
        stored compressed — the TeraPart storage tier; with
        ``ctx.compression.device_decode`` routed on (the terapart presets'
        default) the finest level additionally runs straight off the
        device-resident compressed stream with the decode fused into the
        LP kernels (graph/device_compressed.py), bit-identical to the
        dense path."""
        from .graph.compressed import CompressedGraph, compress

        # A weighted-mode pin auto-detected from a previous graph must not
        # stick to a new one (explicit user pins are kept).
        if self._auto_weighted_pin:
            self.ctx.coarsening.lp.weighted_mode = None
            self._auto_weighted_pin = False
        if graph is not None and not isinstance(graph, CompressedGraph):
            # Heavy-tier input validation inside normal runs (reference:
            # KASSERT heavy validate_graph on every graph adoption,
            # kaminpar.cc:174).  O(m) host-side; active only when the
            # assertion ladder is raised to "heavy".  validate raises
            # ValueError itself (robust under python -O) — the ladder only
            # gates whether it runs.
            from .graph.csr import validate
            from .utils.assertions import HEAVY, assertion_level

            if assertion_level() >= HEAVY:
                validate(graph)
        if isinstance(graph, CompressedGraph):
            self.compressed_graph: Optional[object] = graph
            graph = None
        elif self.ctx.compression.enabled:
            self.compressed_graph = compress(graph)
            Logger.log(
                f"compressed input: {self.compressed_graph.memory_bytes()} B "
                f"({self.compressed_graph.compression_ratio():.2f}x)",
            )
            # Steady-state memory = the compressed copy only; under
            # device_decode routing the finest CSR never materializes at
            # all (the LP kernels decode the stream in-kernel), otherwise
            # it exists transiently inside compute_partition
            # (HBM_BUDGET.md round 14).
            graph = None
        else:
            self.compressed_graph = None
        if graph is not None:
            # Pin this facade's layout-build mode on the graph itself so two
            # KaMinPar instances with different settings cannot reconfigure
            # each other's graphs through the process default; coarse and
            # masked graphs inherit the pin.
            graph._layout_mode = self.ctx.parallel.device_layout_build
        self.graph = graph

    def copy_graph(
        self,
        row_ptr: np.ndarray,
        col_idx: np.ndarray,
        node_weights: Optional[np.ndarray] = None,
        edge_weights: Optional[np.ndarray] = None,
    ) -> None:
        """ParMETIS/CSR-style input (reference: ``copy_graph``,
        kaminpar.cc:179-218).

        Round 17: the facade boundary validates the raw arrays — a
        non-monotone ``row_ptr``, out-of-range column, or
        negative/overflowing weight is rejected here with a typed
        :class:`~kaminpar_tpu.resilience.errors.GraphValidationError`
        instead of surfacing as kernel garbage levels later (the checks
        are vectorized O(n + m); the full symmetry sweep remains on the
        heavy assertion tier)."""
        from .graph.csr import from_numpy_csr

        self.set_graph(
            from_numpy_csr(
                row_ptr, col_idx, node_weights, edge_weights,
                use_64bit=self.ctx.use_64bit_ids,
                validate_input=True,
            )
        )

    # -- partitioning ------------------------------------------------------

    def set_engine(self, engine) -> None:
        """Attach/detach (None) a warm :class:`~kaminpar_tpu.serve.engine.
        PartitionEngine`; subsequent ``compute_partition`` calls are served
        by it (its context governs the pipeline; results are bit-identical
        to a direct run under the same context — tests/test_serve.py)."""
        self._engine = engine

    def compute_partition(
        self,
        k: int,
        epsilon: float = 0.03,
        max_block_weights: Optional[Sequence[int]] = None,
        min_epsilon: float = 0.0,
        min_block_weights: Optional[Sequence[int]] = None,
        resume=None,
    ) -> np.ndarray:
        """``resume`` (round 19): a checkpoint file/directory path (or a
        loaded ``CheckpointState``) from a preempted deep run — the
        fingerprint is validated against this graph/context and the
        pipeline continues from the recorded level boundary,
        BIT-IDENTICAL to the uninterrupted run
        (resilience/checkpoint.py; DEEP mode, dense inputs only).
        Resume always runs in-process (never through an attached
        engine)."""
        if self._engine is not None and self.graph is not None and resume is None:
            # Warm-engine delegation (ISSUE 3): the engine's dispatcher runs
            # the identical facade path on its own long-lived context, so
            # this facade's per-call state (weighted-mode pin, _last) is
            # untouched.  Compressed inputs keep the in-process path — the
            # memory tier's whole point is not materializing the CSR here.
            return self._engine.partition(
                self.graph, k, epsilon,
                max_block_weights=max_block_weights,
                min_epsilon=min_epsilon,
                min_block_weights=min_block_weights,
            )
        try:
            with self.runtime.activate():
                return self._compute_partition(
                    k, epsilon, max_block_weights, min_epsilon,
                    min_block_weights, resume=resume,
                )
        finally:
            # An auto-detected weighted-mode pin is scoped to this call: a
            # caller may mutate the current graph's edge weights in place and
            # re-partition, and must get a fresh detection, not a stale mode.
            # (Explicit user pins are kept.)
            if self._auto_weighted_pin:
                self.ctx.coarsening.lp.weighted_mode = None
                self._auto_weighted_pin = False

    def _compute_partition(
        self,
        k: int,
        epsilon: float = 0.03,
        max_block_weights: Optional[Sequence[int]] = None,
        min_epsilon: float = 0.0,
        min_block_weights: Optional[Sequence[int]] = None,
        resume=None,
    ) -> np.ndarray:
        """Partition into k blocks; returns the (n,) block-id array.

        Balance constraint: per-block weight <=
        ``max((1+epsilon)*ceil(W/k), ceil(W/k) + max_node_weight)`` (the
        reference's setup, kaminpar.cc:315-331), or explicit absolute budgets
        via ``max_block_weights``.  Minimum block weights (enforced by the
        underload balancer) via ``min_epsilon`` (reference:
        ``set_uniform_min_block_weights``) or absolute ``min_block_weights``.
        """
        assert (
            self.graph is not None or self.compressed_graph is not None
        ), "call set_graph/copy_graph first"
        # TeraPart compute tier (VERDICT r2 next-steps #5): with a compressed
        # input the facade never holds the decompressed CSR — budgets come
        # from compressed metadata and the DEEP partitioner materializes /
        # releases the finest level itself.
        graph = self.graph
        cg = self.compressed_graph if graph is None else None
        src = graph if graph is not None else cg
        ctx = self.ctx
        if k <= 0:
            raise ValueError("k must be positive")
        if k > max(src.n, 1):
            raise ValueError(f"k={k} exceeds number of nodes {src.n}")

        RandomState.reseed(ctx.seed)
        Timer.reset_global()
        start = time.perf_counter()

        # Pin the weighted-clustering mode to the *user's* graph so nested
        # extension pipelines (whose subgraphs carry accumulated weights
        # even for unweighted inputs) inherit the decision; see
        # LabelPropagationContext.weighted_mode.  The wrapper above clears
        # auto-pins when this call returns.
        if ctx.coarsening.lp.weighted_mode is None and src.m > 0:
            if graph is not None:
                ctx.coarsening.lp.weighted_mode = not graph.has_uniform_edge_weights()
            else:
                # CompressedGraph stores edge_w=None when all weights are 1.
                cew = cg.edge_w
                ctx.coarsening.lp.weighted_mode = bool(
                    cew is not None and np.min(cew) != np.max(cew)
                )
            self._auto_weighted_pin = True

        total_node_weight = int(src.total_node_weight)
        max_node_weight = (
            int(graph.max_node_weight) if graph is not None
            else int(np.max(cg.node_w, initial=1))
        )
        ctx.partition.setup(total_node_weight, k, epsilon, min_epsilon)
        if max_block_weights is not None:
            max_bw = np.asarray(max_block_weights, dtype=np.int64)
            if max_bw.shape != (k,):
                raise ValueError(
                    f"max_block_weights must have length k={k}, got {max_bw.shape}"
                )
            ctx.partition.max_block_weights = max_bw
        else:
            # strictness adjustment for weighted nodes (kaminpar.cc setup)
            perfect = (total_node_weight + k - 1) // k
            ctx.partition.max_block_weights = np.maximum(
                ctx.partition.max_block_weights, perfect + max_node_weight
            )
        if min_block_weights is not None:
            min_bw = np.asarray(min_block_weights, dtype=np.int64)
            # An empty or mismatched list is a caller error, not "no
            # constraint" (ADVICE r5 #5).
            if min_bw.shape != (k,):
                raise ValueError(
                    f"min_block_weights must have length k={k}, got {min_bw.shape}"
                )
            ctx.partition.min_block_weights = min_bw

        if src.n == 0:
            from .graph.csr import from_numpy_csr

            empty = graph if graph is not None else from_numpy_csr(
                np.zeros(1, dtype=np.int64), np.zeros(0, dtype=np.int64)
            )
            self._last = PartitionedGraph.create(
                empty, k, np.zeros(0, dtype=np.int32),
                ctx.partition.max_block_weights, ctx.partition.min_block_weights,
            )
            return np.zeros(0, dtype=np.int32)

        if resume is not None and (
            graph is None or ctx.mode != PartitioningMode.DEEP
        ):
            raise ValueError(
                "resume= is supported for DEEP-mode dense inputs only "
                "(resilience/checkpoint.py envelope)"
            )

        if graph is None:
            # Isolated-node preprocessing needs a full CSR rebuild; for the
            # memory tier it is skipped — LP's isolated-node clustering
            # (ops/lp.py:cluster_isolated_nodes) handles them in-pipeline.
            partitioner = create_partitioner(ctx, None, compressed=cg)
            p_graph = partitioner.partition()
            self._last = p_graph
            part = np.asarray(p_graph.partition)
            elapsed = time.perf_counter() - start
            log_result_line(
                p_graph.edge_cut(), p_graph.imbalance(),
                metrics.is_feasible(
                    p_graph.graph, part, k, ctx.partition.max_block_weights
                ),
                k, elapsed,
            )
            Logger.log(Timer.global_().machine_readable(), OutputLevel.EXPERIMENT)
            return part

        # Strip isolated nodes before partitioning and bin-pack them into
        # the lightest blocks afterwards (graph/isolated.py, shared with
        # the lane-stacked serve runner whose bit-identity contract
        # requires the exact same strip; RMAT-family graphs are full of
        # isolated nodes).
        from .graph.isolated import strip_isolated_csr

        work_graph = graph
        keep = isolated = None
        stripped = strip_isolated_csr(
            np.asarray(graph.row_ptr),
            lambda: np.asarray(graph.col_idx),
            lambda: np.asarray(graph.node_w),
            graph.n, k,
        )
        if stripped is not None:
            keep, isolated, new_rp, new_col, new_nw = stripped
            from .graph.csr import from_numpy_csr

            work_graph = from_numpy_csr(
                new_rp, new_col, new_nw, np.asarray(graph.edge_w),
                use_64bit=ctx.use_64bit_ids,
            )
            Logger.log(f"Removed {len(isolated)} isolated nodes")

        partitioner = create_partitioner(ctx, work_graph)
        if ctx.mode == PartitioningMode.DEEP:
            # Top-level DEEP runs are checkpoint-eligible (round 19):
            # nested pipelines (extension/v-cycle/dist replicas) never set
            # this flag, so an armed KPTPU_CHECKPOINT cannot make an inner
            # run clobber the outer one's snapshots.  The fingerprint is
            # taken from (and validated against) the isolated-node-stripped
            # work graph — exactly what the partitioner sees.
            partitioner._checkpoint_top_level = True
            if resume is not None:
                from .resilience import checkpoint as _ckpt

                partitioner.resume_state = (
                    resume if isinstance(resume, _ckpt.CheckpointState)
                    else _ckpt.load(resume)
                )
        p_graph = partitioner.partition()

        if keep is not None:
            from .graph.isolated import assign_isolated_nodes

            full_part = assign_isolated_nodes(
                graph.n, k, keep, isolated,
                np.asarray(p_graph.partition),
                np.asarray(work_graph.node_w),
                np.asarray(graph.node_w),
                np.asarray(ctx.partition.max_block_weights, dtype=np.int64),
            )
            p_graph = PartitionedGraph.create(
                graph, k, full_part,
                ctx.partition.max_block_weights, ctx.partition.min_block_weights,
            )
        self._last = p_graph

        part = np.asarray(p_graph.partition)
        # Assertion ladder on the output (reference: partition KASSERTs,
        # kaminpar.cc / partitioned_graph.h): range check at light,
        # feasibility re-check at heavy.
        from .utils.assertions import HEAVY, LIGHT, kassert

        kassert(lambda: part.size == 0 or (part.min() >= 0 and part.max() < k),
                "partition labels out of range", LIGHT)
        kassert(
            lambda: bool(
                metrics.is_feasible(graph, part, k, ctx.partition.max_block_weights)
            ),
            "partition violates block weight caps", HEAVY,
        )
        elapsed = time.perf_counter() - start
        cut = p_graph.edge_cut()
        imb = p_graph.imbalance()
        feas = metrics.is_feasible(graph, part, k, ctx.partition.max_block_weights)
        log_result_line(cut, imb, feas, k, elapsed)
        Logger.log(Timer.global_().machine_readable(), OutputLevel.EXPERIMENT)
        return part

    @property
    def last_partition(self) -> Optional[PartitionedGraph]:
        return self._last
