"""Error surface of the partition-serving runtime.

Mirrors the error taxonomy of standard inference-serving stacks: admission
rejection (backpressure, carries a retry-after hint), deadline expiry,
cancellation, and engine-stopped.  All derive from :class:`ServeError` so
callers can catch the whole family at once.

Round 17: these are the *control-flow* outcomes of admission and request
lifecycle; *failures* (execute faults, compile timeouts, capacity
exhaustion, backend loss, poisoned cells, hung workers) are typed by the
unified taxonomy in :mod:`kaminpar_tpu.resilience.errors` — every
dispatch-site ``except`` routes through ``resilience.errors.classify``
(enforced by the kptlint ``error-discipline`` rule), and
``classify``/``is_control_flow`` pass this module's classes through
untouched so admission semantics never change under classification.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class of every serving-runtime error."""


class QueueFullError(ServeError):
    """Admission control rejected the request: the bounded queue is full.

    ``retry_after_s`` is the engine's estimate of when capacity frees up
    (queue depth x smoothed per-request service time / batch width) — the
    standard reject-with-retry-after backpressure contract."""

    def __init__(self, retry_after_s: float = 0.1):
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"serve queue full; retry after {self.retry_after_s:.3f}s"
        )


class CapacityError(ServeError):
    """Admission preflight rejected the request: its predicted HBM
    watermark exceeds the engine's per-device ceiling (ISSUE 12; see
    telemetry/capacity.py).  Raised BEFORE the request is queued — nothing
    was compiled or dispatched.  Carries the prediction so SLO-aware
    routers can steer the request to a bigger device instead of retrying.
    """

    def __init__(self, predicted_bytes: int, ceiling_bytes: int,
                 cell=(), device_kind: str = ""):
        self.predicted_bytes = int(predicted_bytes)
        self.ceiling_bytes = int(ceiling_bytes)
        self.cell = tuple(cell)
        self.device_kind = device_kind
        super().__init__(
            f"predicted HBM watermark {self.predicted_bytes} B exceeds the "
            f"{device_kind or 'device'} admission ceiling "
            f"{self.ceiling_bytes} B for shape cell {self.cell} "
            "(telemetry/capacity.py; raise ServeContext.capacity_ceiling_"
            "bytes or use a larger device kind)"
        )


class DeadlineExceededError(ServeError):
    """The request's deadline expired before execution started.

    A dispatched XLA computation is not interruptible, so deadlines are
    enforced at admission and at batch formation — a request that starts
    executing runs to completion."""


class RequestCancelledError(ServeError):
    """The request was cancelled (``ServeFuture.cancel``) before it ran."""


class EngineStoppedError(ServeError):
    """The engine is not running (never started, draining, or shut down)."""
