"""Serving-runtime metrics: counters, occupancy, latency percentiles.

The structured snapshot the engine exposes (``PartitionEngine.stats()``)
is built on the existing observability layers — ``utils/compile_stats``
(distinct compiled shapes + compile seconds), ``utils/sync_stats``
(blocking-transfer census), and the timer tree's phase names — plus the
serving-specific signals an operator needs: queue depth, admission /
reject / timeout counts, micro-batch occupancy, warm-cache hit rate, and
per-phase latency percentiles (queue wait, execute, total).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class LatencyReservoir:
    """Fixed-capacity ring of samples; summarizes to p50/p90/p99/mean/max.

    A ring (latest ``cap`` samples win) keeps steady-state serving numbers
    current instead of diluting them with warmup-era outliers."""

    def __init__(self, cap: int = 4096):
        self._cap = int(cap)
        self._buf = np.zeros(self._cap, dtype=np.float64)
        self._next = 0
        self._count = 0

    def add(self, value: float) -> None:
        self._buf[self._next % self._cap] = float(value)
        self._next += 1
        self._count = min(self._count + 1, self._cap)

    def summary(self) -> Dict[str, float]:
        if self._count == 0:
            return {"count": 0}
        vals = self._buf[: self._count]
        p50, p90, p99 = np.percentile(vals, [50, 90, 99])
        return {
            "count": int(self._count if self._next <= self._cap else self._next),
            "p50": round(float(p50), 3),
            "p90": round(float(p90), 3),
            "p99": round(float(p99), 3),
            "mean": round(float(vals.mean()), 3),
            "max": round(float(vals.max()), 3),
        }


class ServeStats:
    """Thread-safe accumulator for the engine's serving metrics."""

    _COUNTERS = (
        "submitted", "admitted", "rejected_full", "rejected_capacity",
        "timed_out", "cancelled",
        "completed", "failed", "batches", "warm_hits", "warm_misses",
        # Lane-stacked execution census (round 11, serve/lanestack.py):
        # batches run as one vmapped stack, total lanes they carried,
        # cohort splits inside them, and batches that fell back to the
        # per-graph loop.
        "lanestacked_batches", "lanestacked_lanes", "lanestack_splits",
        "lanestack_fallbacks",
        # Resilience census (round 17, kaminpar_tpu/resilience): fast
        # admission rejects from a poisoned (open-breaker) shape cell,
        # in-flight requests force-resolved by the bounded drain after the
        # worker died/hung, watchdog deadline overruns, strong->fast
        # quality demotions, and contained warmup-pass faults.
        "rejected_poisoned", "worker_hung", "watchdog_timeouts",
        "demoted_quality", "warmup_faults",
        # Crash-safe journal census (round 19, serve/journal.py):
        # unresolved admits re-enqueued at start() and resolution records
        # appended at first-wins finalization — replay conservation means
        # every journaled admit eventually gains exactly ONE resolution.
        "journal_replayed", "journal_resolutions",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Zero everything (bench sweep points reset between loads)."""
        with self._lock:
            self._c = {name: 0 for name in self._COUNTERS}
            self._occupancy_sum = 0
            self._occupancy_max = 0
            self._lat = {
                "queue_wait_ms": LatencyReservoir(),
                "execute_ms": LatencyReservoir(),
                "total_ms": LatencyReservoir(),
            }
            # Smoothed per-request service seconds; feeds the retry-after
            # estimate of the admission-reject path.
            self.ema_service_s = 0.0

    def bump(self, counter: str, by: int = 1) -> None:
        with self._lock:
            self._c[counter] += by

    def record_warm(self, hit: bool) -> None:
        self.bump("warm_hits" if hit else "warm_misses")

    def record_batch(self, occupancy: int) -> None:
        with self._lock:
            self._c["batches"] += 1
            self._occupancy_sum += int(occupancy)
            self._occupancy_max = max(self._occupancy_max, int(occupancy))

    def record_request(
        self, queue_wait_s: float, execute_s: float, failed: bool = False,
        service_s: Optional[float] = None,
    ) -> None:
        """Latency percentiles take ``execute_s`` (a lane-stacked request's
        amortized share); the retry-after EMA takes ``service_s`` — the
        UNAMORTIZED cost of the dispatch that served the request (the batch
        wall for lane-stacked work) — because :meth:`retry_after_estimate`
        divides the EMA by the batch width itself.  None = execute_s."""
        with self._lock:
            self._c["failed" if failed else "completed"] += 1
            self._lat["queue_wait_ms"].add(queue_wait_s * 1e3)
            self._lat["execute_ms"].add(execute_s * 1e3)
            self._lat["total_ms"].add((queue_wait_s + execute_s) * 1e3)
            alpha = 0.2
            svc = execute_s if service_s is None else service_s
            self.ema_service_s = (
                svc if self.ema_service_s == 0.0
                else (1 - alpha) * self.ema_service_s + alpha * svc
            )

    def seed_service_time(self, seconds: float) -> None:
        """Initialize the service-time EMA from the warmup report's warm
        execution cost (ISSUE 6 satellite): retry-after estimates are real
        from the first admission reject instead of falling back to a blind
        floor until the first completion.  A live EMA (completions already
        recorded) is never overwritten."""
        with self._lock:
            if self.ema_service_s == 0.0 and seconds > 0.0:
                self.ema_service_s = float(seconds)

    def execute_p99_s(self) -> float:
        """p99 of the execute-stage reservoir in SECONDS (0.0 before any
        sample) — the fleet router's tail-latency steering term, read
        without materializing the full snapshot."""
        with self._lock:
            summary = self._lat["execute_ms"].summary()
        return float(summary.get("p99", 0.0)) / 1e3

    def service_time_estimate(self) -> float:
        """The smoothed UNAMORTIZED per-request service seconds (the EMA
        the retry-after estimate divides by the batch width; 0.0 before
        any completion or warmup seed)."""
        with self._lock:
            return float(self.ema_service_s)

    def retry_after_estimate(self, queue_depth: int, max_batch: int) -> float:
        """Backpressure hint: depth x smoothed service time / batch width,
        floored so callers never busy-spin on a zero.  The EMA is seeded
        from warmup (:meth:`seed_service_time`), so the pre-first-completion
        fallback constant only applies to engines started without warmup."""
        with self._lock:
            per = self.ema_service_s or 0.1
        return max(0.05, queue_depth * per / max(1, max_batch))

    def counter(self, name: str) -> int:
        with self._lock:
            return self._c[name]

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """Structured stats record (every field documented in the README
        "Serving" section)."""
        from ..utils import compile_stats, sync_stats

        with self._lock:
            counts = dict(self._c)
            batches = counts["batches"]
            out = {
                **counts,
                "batch_occupancy_mean": round(
                    self._occupancy_sum / batches, 3
                ) if batches else 0.0,
                "batch_occupancy_max": self._occupancy_max,
                "warm_hit_rate": round(
                    counts["warm_hits"]
                    / max(1, counts["warm_hits"] + counts["warm_misses"]),
                    4,
                ),
                # Mean lanes per stacked batch — the realized device
                # parallelism of the lane-stacked path.
                "lanestack_occupancy_mean": round(
                    counts["lanestacked_lanes"]
                    / counts["lanestacked_batches"], 3
                ) if counts["lanestacked_batches"] else 0.0,
                "latency_ms": {k: v.summary() for k, v in self._lat.items()},
                "ema_service_s": round(self.ema_service_s, 4),
            }
        if queue_depth is not None:
            out["queue_depth"] = int(queue_depth)
        out["compiled_shape_count"] = compile_stats.snapshot()
        out["compile"] = compile_stats.compile_time_snapshot()
        sync_snap = sync_stats.snapshot()
        out["host_sync_count"] = sync_snap["count"]
        out["host_sync_bytes"] = sync_snap["bytes"]
        # Collective-traffic census (round 13, utils/collective_stats.py):
        # traced psum/all_to_all/all_gather ops + logical bytes — zero for
        # a pure-shm engine, populated the moment a dist/mesh pipeline
        # shares the process.
        from ..utils import collective_stats

        coll = collective_stats.snapshot()
        out["collective_count"] = coll["count"]
        out["collective_logical_bytes"] = coll["logical_bytes"]
        out["collective_by_op"] = {
            op: row["count"] for op, row in coll["by_op"].items()
        }
        return out

    def prometheus_families(
        self,
        queue_depth: Optional[int] = None,
        running: Optional[bool] = None,
        warm_cells: Optional[int] = None,
    ) -> list:
        """The snapshot as Prometheus metric families (ISSUE 5):
        ``[(name, type, help, [(labels, value), ...]), ...]`` rendered by
        :mod:`kaminpar_tpu.telemetry.prometheus` into
        ``PartitionEngine.metrics_text()`` / the serve CLI's ``/metrics``
        endpoint."""
        snap = self.snapshot(queue_depth=queue_depth)
        outcome_counters = (
            "submitted", "admitted", "rejected_full", "rejected_capacity",
            "rejected_poisoned", "timed_out", "cancelled", "completed",
            "failed", "worker_hung",
        )
        lat_samples = []
        count_samples = []
        for stage, summary in snap["latency_ms"].items():
            base = stage[:-3] if stage.endswith("_ms") else stage
            count_samples.append(({"stage": base}, summary.get("count", 0)))
            for quantile, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                if key in summary:
                    lat_samples.append(
                        ({"stage": base, "quantile": quantile}, summary[key])
                    )
        return [
            ("kaminpar_serve_queue_depth", "gauge",
             "Requests currently waiting in the bounded queue",
             [({}, snap.get("queue_depth"))]),
            ("kaminpar_serve_requests_total", "counter",
             "Requests by admission/completion outcome",
             [({"outcome": name}, snap[name]) for name in outcome_counters]),
            ("kaminpar_serve_warm_lookups_total", "counter",
             "Warm-cache lookups by result",
             [({"result": "hit"}, snap["warm_hits"]),
              ({"result": "miss"}, snap["warm_misses"])]),
            ("kaminpar_serve_warm_hit_rate", "gauge",
             "Fraction of submissions landing in a warmed shape cell",
             [({}, snap["warm_hit_rate"])]),
            ("kaminpar_serve_batches_total", "counter",
             "Micro-batches dispatched",
             [({}, snap["batches"])]),
            ("kaminpar_serve_batch_occupancy", "gauge",
             "Requests per dispatched micro-batch",
             [({"stat": "mean"}, snap["batch_occupancy_mean"]),
              ({"stat": "max"}, snap["batch_occupancy_max"])]),
            ("kaminpar_serve_lanestack_batches_total", "counter",
             "Micro-batches by lane-stack execution outcome",
             [({"result": "stacked"}, snap["lanestacked_batches"]),
              ({"result": "fallback"}, snap["lanestack_fallbacks"])]),
            ("kaminpar_serve_lanestack_lanes_total", "counter",
             "Total lanes executed by the lane-stacked pipeline",
             [({}, snap["lanestacked_lanes"])]),
            ("kaminpar_serve_lanestack_splits_total", "counter",
             "Cohort splits inside lane-stacked batches (a high split rate "
             "means lanes diverged and degenerated toward per-lane cohorts "
             "— mandatory context for any lane-stack throughput figure)",
             [({}, snap["lanestack_splits"])]),
            ("kaminpar_serve_lanestack_occupancy", "gauge",
             "Mean lanes per lane-stacked batch",
             [({}, snap["lanestack_occupancy_mean"])]),
            ("kaminpar_serve_resilience_events_total", "counter",
             "Resilience-layer events: watchdog deadline overruns, "
             "strong->fast quality demotions, contained warmup faults "
             "(round 17; breaker detail rides the "
             "kaminpar_resilience_* families)",
             [({"event": "watchdog_timeout"}, snap["watchdog_timeouts"]),
              ({"event": "demoted_quality"}, snap["demoted_quality"]),
              ({"event": "warmup_fault"}, snap["warmup_faults"])]),
            ("kaminpar_serve_latency_ms", "gauge",
             "Latency percentiles in milliseconds over the rolling reservoir",
             lat_samples),
            ("kaminpar_serve_latency_samples", "gauge",
             "Total latency samples recorded per stage (the percentile "
             "reservoir keeps only the most recent window)",
             count_samples),
            ("kaminpar_serve_ema_service_seconds", "gauge",
             "Smoothed per-request service time feeding retry-after estimates",
             [({}, snap["ema_service_s"])]),
            ("kaminpar_serve_host_sync_transfers_total", "counter",
             "Blocking device-to-host transfers (process-wide census)",
             [({}, snap["host_sync_count"])]),
            ("kaminpar_serve_host_sync_bytes_total", "counter",
             "Bytes moved by blocking device-to-host transfers (process-wide)",
             [({}, snap["host_sync_bytes"])]),
            ("kaminpar_collective_ops_total", "counter",
             "Traced mesh collectives by op (process-wide census; counts "
             "are per compiled specialization, see utils/collective_stats)",
             [({"op": op}, count)
              for op, count in sorted(snap["collective_by_op"].items())]
             or [({}, 0)]),
            ("kaminpar_collective_logical_bytes_total", "counter",
             "Logical payload bytes of traced mesh collectives "
             "(per-shard operand bytes x axis size; not wire bytes)",
             [({}, snap["collective_logical_bytes"])]),
            ("kaminpar_serve_compiled_shapes", "gauge",
             "Distinct compiled kernel specializations (process-wide census)",
             [({}, snap["compiled_shape_count"].get("total", 0))]),
            ("kaminpar_serve_running", "gauge",
             "Whether the engine dispatcher is accepting work",
             [({}, None if running is None else int(bool(running)))]),
            ("kaminpar_serve_warm_cells", "gauge",
             "Distinct (n-bucket, m-bucket, k) cells warmed so far",
             [({}, warm_cells)]),
        ]
