"""Bucket-batched multi-graph packing for the serving runtime.

Requests landing in the same shape cell — ``(node-bucket, edge-bucket, k)``
on the sqrt(2) geometric ladder of :func:`utils.intmath.next_shape_bucket`,
the same ladder every ``CSRGraph.padded()`` view compiles against — are
micro-batched.  The batch's graphs are packed as *disjoint components* into
one union CSR buffer (host-side concatenation with node-id offsets; the
components never share an edge, so per-graph structure is preserved
exactly), and per-graph quality metrics for the whole batch are computed in
a **single dispatch** over the packed buffer via graph-id segment
reductions (:func:`batched_metrics`), with one batched readback for all of
them — the one-pull discipline of PR 2.

The partitions themselves are produced per graph by the engine's warm
pipeline (serve/engine.py) so they stay bit-identical to sequential
``KaMinPar.compute_partition`` runs — the identity discipline PR 1/2
established for kernels; tests/test_serve.py asserts it — and are then
validated/unpacked against the packed buffer here.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.csr import CSRGraph, _next_bucket
from ..utils import sync_stats


class ShapeCell(NamedTuple):
    """Batching key: two padded-shape rungs plus the block count."""

    n_bucket: int
    m_bucket: int
    k: int


def shape_cell(graph, k: int) -> ShapeCell:
    """The (node-bucket, edge-bucket, k) cell a request lands in.  Uses the
    same geometric ladder (and the same minimum rung) as
    ``CSRGraph.padded()``, so one cell == one set of top-level compile
    shapes."""
    return ShapeCell(_next_bucket(graph.n), _next_bucket(graph.m), int(k))


class PackedBatch(NamedTuple):
    """Disjoint union of a batch's graphs plus unpack metadata.

    ``node_offsets``/``edge_offsets`` are (b+1,) prefix sums; graph ``i``
    owns nodes ``[node_offsets[i], node_offsets[i+1])`` of the union.
    ``node_gid``/``edge_gid`` map every union slot back to its graph."""

    union: CSRGraph
    node_offsets: np.ndarray
    edge_offsets: np.ndarray
    node_gid: np.ndarray
    edge_gid: np.ndarray

    @property
    def num_graphs(self) -> int:
        return len(self.node_offsets) - 1


def pack_graphs(graphs: Sequence[CSRGraph]) -> PackedBatch:
    """Pack graphs as disjoint components into one padded-buffer-ready CSR.

    Host-side (batch formation is orchestration): concatenates the CSR
    arrays with node-id offsets.  The union is a structurally valid graph —
    ``graph.csr.validate`` accepts it — whose padded view lands on the
    bucket ladder like any other graph."""
    if not graphs:
        raise ValueError("cannot pack an empty batch")
    use_64 = any(g.row_ptr.dtype == np.int64 for g in graphs)  # metadata read
    idt = np.int64 if use_64 else np.int32
    n_off = np.zeros(len(graphs) + 1, dtype=np.int64)
    m_off = np.zeros(len(graphs) + 1, dtype=np.int64)
    np.cumsum([g.n for g in graphs], out=n_off[1:])
    np.cumsum([g.m for g in graphs], out=m_off[1:])
    row_ptr = np.zeros(int(n_off[-1]) + 1, dtype=idt)
    col_idx = np.empty(int(m_off[-1]), dtype=idt)
    node_w = np.empty(int(n_off[-1]), dtype=idt)
    edge_w = np.empty(int(m_off[-1]), dtype=idt)
    node_gid = np.empty(int(n_off[-1]), dtype=np.int32)
    edge_gid = np.empty(int(m_off[-1]), dtype=np.int32)
    for i, g in enumerate(graphs):
        ns, ne = int(n_off[i]), int(n_off[i + 1])
        ms, me = int(m_off[i]), int(m_off[i + 1])
        # ONE counted batched readback per member graph (round 12, kptlint
        # sync-discipline: formerly four un-counted np.asarray transfers;
        # zero-copy on the CPU backend, a real pull on accelerators).
        rp_h, col_h, nw_h, ew_h = sync_stats.pull(
            g.row_ptr, g.col_idx, g.node_w, g.edge_w, phase="serve_pack"
        )
        row_ptr[ns + 1 : ne + 1] = rp_h[1:] + ms
        col_idx[ms:me] = col_h + ns
        node_w[ns:ne] = nw_h
        edge_w[ms:me] = ew_h
        node_gid[ns:ne] = i
        edge_gid[ms:me] = i
    union = CSRGraph(row_ptr, col_idx, node_w, edge_w)
    # The union inherits the first member's layout ownership (all members
    # of a batch belong to the same engine; kptlint runtime-isolation).
    union._layout_mode = getattr(graphs[0], "_layout_mode", None)
    return PackedBatch(union, n_off, m_off, node_gid, edge_gid)


def unpack_partition(labels: np.ndarray, node_offsets: np.ndarray) -> List[np.ndarray]:
    """Split a union-node-space label array back into per-graph arrays
    (host arrays in, host arrays out — the engine pulls before unpacking)."""
    labels = np.asarray(labels)
    return [
        labels[int(node_offsets[i]) : int(node_offsets[i + 1])]
        for i in range(len(node_offsets) - 1)
    ]


def form_batches(requests: Sequence, max_batch: int) -> List[list]:
    """Group requests into same-cell batches of at most ``max_batch``,
    FIFO-fair: each batch is seeded by the oldest unbatched request and
    collects later same-cell requests in arrival order.  Items must carry a
    ``.cell`` attribute (``ServeRequest`` does)."""
    batches: List[list] = []
    remaining = list(requests)
    while remaining:
        cell = remaining[0].cell
        take = [r for r in remaining if r.cell == cell][: max(1, int(max_batch))]
        taken = set(map(id, take))
        remaining = [r for r in remaining if id(r) not in taken]
        batches.append(take)
    return batches


@partial(jax.jit, static_argnames=("num_graphs", "k"))
def _packed_metrics(edge_u, col_idx, edge_w, labels, edge_gid, node_w,
                    node_gid, num_graphs: int, k: int):
    """Per-graph edge cuts + block weights of a packed batch, one dispatch.

    Graph-id segment reductions over the union buffer; pad slots are inert
    (weight 0) exactly as in graph/metrics.py.  Returns one flat int64
    array ``[cut_0..cut_{b-1}, bw_0_0..bw_{b-1}_{k-1}]`` so the caller
    needs a single batched readback for the whole batch."""
    from ..utils import compile_stats

    compile_stats.record(
        "serve_packed_metrics", (edge_u, labels), (num_graphs, k)
    )
    cut = labels[edge_u] != labels[col_idx]
    cuts = (
        jax.ops.segment_sum(
            jnp.where(cut, edge_w, 0), edge_gid, num_segments=num_graphs
        )
        // 2
    )
    seg = node_gid * k + labels.astype(node_gid.dtype)
    bw = jax.ops.segment_sum(
        node_w.astype(edge_w.dtype), seg, num_segments=num_graphs * k
    )
    return jnp.concatenate([cuts, bw])


def batched_metrics(
    packed: PackedBatch,
    parts: Sequence[np.ndarray],
    k: int,
    pad_to: Optional[int] = None,
):
    """(cuts (b,), block_weights (b, k)) for every graph of the batch —
    single dispatch over the packed union buffer, single counted readback
    (utils/sync_stats phase ``serve_batch_metrics``).

    ``pad_to`` buckets the static graph-count at the engine's max batch:
    the trailing segments simply sum nothing, so the kernel compiles once
    per (union bucket, k, max_batch) instead of once per occupancy level
    — the same specialization-count discipline as the shape ladder."""
    from ..utils import sync_stats

    b = packed.num_graphs
    nb = max(b, int(pad_to or 0))
    pv = packed.union.padded()
    labels = np.zeros(pv.n_pad, dtype=np.int32)
    labels[: pv.n] = np.concatenate(list(parts))
    egid = np.zeros(pv.m_pad, dtype=np.int32)
    egid[: pv.m] = packed.edge_gid
    ngid = np.zeros(pv.n_pad, dtype=np.int32)
    ngid[: pv.n] = packed.node_gid
    flat = _packed_metrics(
        pv.edge_u, pv.col_idx, pv.edge_w, jnp.asarray(labels),
        jnp.asarray(egid), pv.node_w, jnp.asarray(ngid),
        num_graphs=nb, k=int(k),
    )
    flat = sync_stats.pull(flat, phase="serve_batch_metrics")
    return flat[:b], flat[nb:].reshape(nb, int(k))[:b]
