"""Mesh-replicated serve fleet: a shape-cell router over per-device
engine replicas (round 18; the ROADMAP "millions of users" tier).

:class:`PartitionFleet` scales the single warm :class:`PartitionEngine`
to a fleet — one engine replica per mesh device (CPU dryrun: the forced
virtual host devices the ``shard_ab`` bench already uses), each pinned to
its device through the :class:`~kaminpar_tpu.context.EngineRuntime`
placement hook.  The front router classifies every request by its
existing :func:`~kaminpar_tpu.serve.batching.shape_cell` and steers it
with an **SLO-aware score** over the replicas' live serving signals —
queue drain estimate (the unamortized service-time EMA times depth over
batch width), p99 execute seconds, open breakers for the request's cell,
and the capacity-preflight verdict — instead of a single EMA:

* **lane x device 2D execution** — same-cell load fans *in* per replica
  up to the engine's ``max_batch`` (a score bonus for joining a forming
  batch fills the lane axis, where PR 6's vmapped lane-stacked dispatch
  runs the whole micro-batch as ONE program), then spills to the next
  device (the device axis).  Aggregate occupancy = replicas x lanes.
* **graph-id-sticky routing** — a request carrying ``graph_id`` keeps
  landing on the replica that first served it while that replica stays
  healthy, so a tenant's warm graph state stays device-local (the hook
  the incremental-repartitioning ROADMAP item composes with).
* **warm-cache inheritance** — replica N+1 shares the fleet's persistent
  compilation cache dir and imports the first replica's warmup report
  (:meth:`PartitionEngine.inherit_warmup`), skipping every cell already
  traced; inherited-vs-local counts ride ``warmup_report``/Prometheus.
* **drain + cross-replica resteer** — a replica whose watchdog trips or
  whose cell breakers latch open is drained: queued work is requeued on
  healthy replicas eagerly, in-flight work finishes (or is force-resolved
  typed by PR 13's bounded-drain machinery and resteered lazily), and
  nothing is lost or resolved twice (:class:`FleetFuture` rebinds with
  first-wins finalization).  The fleet-scoped ``replica`` breaker rung
  restores a drained replica through the standard half-open probe.

* **elastic scaling** (round 19) — :meth:`PartitionFleet.scale_to`
  resizes the fleet under live traffic: scale-up revives retired slots
  (warm state carries over) before spawning fresh inheriting replicas;
  scale-down retires the highest-index replicas through the drain/
  resteer machinery with conserved resolutions.  An optional
  ``autoscale`` policy (queue-drain watermarks with hysteresis, driven
  from the submit-path health sweep) sizes the fleet automatically, and
  a replica the health sweep takes out is *replaced*, not just drained
  (``replace_drained``).  Census in ``stats()`` ``fleet_scale_*``
  counters / ``kaminpar_fleet_scale_total``.

CPU-dryrun honesty: virtual host devices SERIALIZE — a CPU fleet number
is a router/occupancy claim, not a parallel-speedup claim; the
device-axis throughput claim rides tpu_prober (TPU_NOTES round 18).
"""

from __future__ import annotations

import copy
import itertools
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Union

from ..context import Context, FleetContext
from ..resilience.breakers import BreakerRegistry
from ..resilience.errors import ExecuteFault, WorkerHung
from .batching import ShapeCell, shape_cell
from .engine import PartitionEngine, ServeFuture, ServeResult
from .errors import EngineStoppedError, QueueFullError


def _is_resteerable(exc: BaseException) -> bool:
    """Failures that mean "this replica gave the request back", not "this
    request is bad": a draining replica rejecting queued work, a hung
    dispatcher's bounded-drain force-resolution, and the watchdog's typed
    abandonment of an in-flight batch.  Everything else (deadline, cancel,
    a genuine pipeline fault) surfaces to the caller unchanged."""
    if isinstance(exc, (EngineStoppedError, WorkerHung)):
        return True
    return isinstance(exc, ExecuteFault) and getattr(exc, "site", "") in (
        "watchdog", "shutdown"
    )


class _FleetRecord:
    """Mutable routing state of one fleet request (internal)."""

    __slots__ = (
        "fleet_id", "graph", "k", "epsilon", "kwargs", "graph_id",
        "replica", "current", "attempts", "lock", "trace_id",
    )

    def __init__(self, fleet_id: int, graph, k: int, epsilon: float,
                 kwargs: dict, graph_id, trace_id: str = ""):
        self.fleet_id = fleet_id
        self.graph = graph
        self.k = int(k)
        self.epsilon = float(epsilon)
        self.kwargs = dict(kwargs)
        self.graph_id = graph_id
        self.replica: int = -1
        self.current: Optional[ServeFuture] = None
        self.attempts = 0
        self.lock = threading.Lock()
        # Request-scoped trace id (round 20): minted at steer time and
        # passed to every engine submit this record makes, so the whole
        # cross-replica life is one connected event chain.
        self.trace_id = str(trace_id)


class FleetFuture:
    """Completion handle for a fleet-routed request.

    Wraps the engine-level :class:`ServeFuture` the request is currently
    bound to; when that future resolves with a *resteerable* typed error
    (the bound replica drained or hung), the waiter triggers a
    cross-replica requeue and re-waits on the new binding.  Finalization
    is first-wins: exactly one result (or terminal error) per request,
    however many times the binding moved."""

    def __init__(self, fleet: "PartitionFleet", record: _FleetRecord):
        self._fleet = fleet
        self._record = record
        self._final_result: Optional[ServeResult] = None
        self._final_error: Optional[BaseException] = None
        self._finalized = threading.Event()
        self._lock = threading.Lock()

    @property
    def fleet_id(self) -> int:
        return self._record.fleet_id

    @property
    def replica(self) -> int:
        """Index of the replica currently (or finally) serving this
        request — may change across resteers."""
        return self._record.replica

    def cancel(self) -> bool:
        # Lock-free attribute read: ``current`` swaps atomically under the
        # record lock, and a resteer may hold that lock through bounded
        # backpressure waits — cancel/done must stay non-blocking (a
        # stale read here at worst cancels the abandoned binding, which
        # the resteer already gave up on).
        fut = self._record.current
        return fut.cancel() if fut is not None else False

    def done(self) -> bool:
        if self._finalized.is_set():
            return True
        fut = self._record.current  # lock-free: see cancel()
        return fut is not None and fut.done()

    def _finalize(self, result=None, error=None) -> None:
        with self._lock:
            if self._finalized.is_set():
                return
            self._final_result = result
            self._final_error = error
            self._finalized.set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        deadline = None if timeout is None else time.monotonic() + timeout
        rec = self._record
        while True:
            if self._finalized.is_set():
                if self._final_error is not None:
                    raise self._final_error
                assert self._final_result is not None
                return self._final_result
            # Lock-free read (see cancel()): _resteer holds rec.lock
            # through bounded backpressure sleeps — taking it here would
            # block a result(timeout=...) caller past its deadline.  A
            # stale binding is safe: it resolves with the typed abandon
            # error and the loop re-reads after _maybe_resteer.
            fut = rec.current
            assert fut is not None
            remaining = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            try:
                res = fut.result(remaining)
            except TimeoutError:
                if self._finalized.is_set():
                    continue  # another waiter finalized while we timed out
                raise
            except Exception as exc:
                if self._fleet._maybe_resteer(rec, fut, exc):
                    continue  # rebound to a healthy replica; re-wait
                self._finalize(error=exc)
                self._fleet._forget(rec)
                raise
            self._fleet._note_success(rec)
            self._finalize(result=res)
            self._fleet._forget(rec)
            return res


class PartitionFleet:
    """Front router over N per-device :class:`PartitionEngine` replicas.

    Usage::

        from kaminpar_tpu.serve import PartitionFleet
        with PartitionFleet("serve", replicas=8) as fleet:
            fut = fleet.submit(graph, k=8, graph_id="tenant-42")
            part = fut.result().partition

    Thread model: ``submit`` steers from any caller thread (pure host
    arithmetic under the registered ``fleet_steer`` phase); each replica
    keeps its own single dispatcher thread, so device work per replica
    stays serialized and per-request determinism is inherited from the
    engine contract (asserted across replicas in tests/test_fleet.py).
    """

    def __init__(
        self,
        ctx: Union[Context, str, None] = None,
        replicas: Optional[int] = None,
        **serve_overrides,
    ):
        from ..presets import create_context_by_preset_name

        if ctx is None:
            ctx = create_context_by_preset_name("serve")
        elif isinstance(ctx, str):
            ctx = create_context_by_preset_name(ctx)
        else:
            ctx = copy.deepcopy(ctx)
        self.ctx = ctx
        self.fleet_ctx: FleetContext = ctx.fleet
        n = int(replicas if replicas is not None else self.fleet_ctx.replicas)
        if n <= 0:
            import jax

            n = len(jax.devices())
        # One shared persistent cache dir for the whole fleet (warm-cache
        # inheritance leg 1): resolve the base context's settings once and
        # pin every replica to the same dir.
        from ..context import _resolve_cache_settings

        cache_enabled, cache_dir = _resolve_cache_settings(ctx.parallel)
        self._cache_enabled, self._cache_dir = cache_enabled, cache_dir
        self._serve_overrides = dict(serve_overrides)
        self.replicas: List[PartitionEngine] = []
        for i in range(n):
            rctx = copy.deepcopy(ctx)
            rctx.parallel.placement_device = i
            if cache_enabled and cache_dir:
                rctx.parallel.compilation_cache_dir = cache_dir
            if rctx.serve.journal_path:
                # Per-replica journal files (round 19): one shared path
                # would interleave N engines' records with colliding
                # request ids, making replay ambiguous.
                rctx.serve.journal_path += f".replica{i}"
            self.replicas.append(
                PartitionEngine(rctx, name=f"replica{i}", **serve_overrides)
            )
        # ONE request-trace registry for the whole fleet (round 20,
        # telemetry/reqtrace.py): replicas share it so a request resteered
        # off a draining replica keeps one connected event chain across
        # engines (each engine-private registry would fragment the
        # dossier).  _spawn_replica re-attaches it to fresh replicas.
        from ..telemetry.reqtrace import ReqTrace

        self.reqtrace = ReqTrace()
        for eng in self.replicas:
            eng.reqtrace = self.reqtrace
        # Fleet-scoped breaker registry (round 18): one "replica" breaker
        # per replica index — tripped by drain_replica, restored by the
        # half-open probe at steering time (which restarts the engine).
        self.breakers = BreakerRegistry(
            threshold=ctx.resilience.breaker_threshold,
            cooldown_s=self.fleet_ctx.replica_cooldown_s,
            scope="fleet",
        )
        self._draining = [False] * n
        self._drain_threads: List[Optional[threading.Thread]] = [None] * n
        self._watchdog_seen = [0] * n
        # Elastic membership (round 19): a RETIRED slot was scaled down
        # on purpose — unroutable, NOT probe-restorable (an intentional
        # drain is not a health verdict) — and is revived cheaply by the
        # next scale-up (warm state carries over engine restarts).
        self._retired = [False] * n
        self._sticky: Dict[object, int] = {}
        self._records: Dict[int, _FleetRecord] = {}  # id(engine future) ->
        self._counters: Dict[str, int] = {
            "submitted": 0, "resteers": 0, "sticky_hits": 0,
            "sticky_moves": 0, "drains": 0, "restores": 0,
            "rejected_full": 0, "rejected_unroutable": 0,
            "rejected_capacity": 0,
            "steer_retries": 0, "probe_steers": 0,
            # Elastic-scaling census (round 19, ISSUE 15): scale_to
            # calls by direction, how each slot changed (fresh spawn vs
            # retired-slot revival vs retirement), health-sweep
            # replacements, and autoscale decisions.
            "fleet_scale_ups": 0, "fleet_scale_downs": 0,
            "fleet_scale_spawns": 0, "fleet_scale_revives": 0,
            "fleet_scale_retires": 0, "fleet_scale_replacements": 0,
            "fleet_scale_auto_ups": 0, "fleet_scale_auto_downs": 0,
        }
        # Autoscale hysteresis state: consecutive health sweeps above the
        # high / below the low queue-drain watermark.
        self._above_high = 0
        self._below_low = 0
        self._scale_lock = threading.Lock()
        # Sweep-triggered scaling (autoscale / replacement) runs on this
        # single background thread — spawning + warming a replica can
        # take seconds and must never block the submit path that happened
        # to run the health sweep.  One action at a time: a pending
        # action absorbs further triggers (the next sweep re-evaluates).
        self._bg_scale: Optional[threading.Thread] = None
        self._warmup_flag = True
        # Submit-path health-check throttle: the auto-drain sweep reads
        # every replica's signals — once per interval, not per request.
        self._health_interval_s = 0.05
        self._last_health_check = 0.0
        # Record-map prune watermark (done futures whose waiter never
        # returned; pruning only drops the drain-lookup entry — live
        # waiters hold the record object itself).
        self._prune_watermark = max(
            64, 2 * sum(r.serve.queue_bound for r in self.replicas)
        )
        # Sticky-map bound: LRU eviction past the watermark (reads
        # refresh recency) — tenant cardinality must not grow router
        # memory without bound.
        self._sticky_watermark = max(4096, 8 * self._prune_watermark)
        self._steered = [0] * n
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._started = False
        self._stopping = False

    @property
    def serve(self):
        """The fleet's serve knobs (replica 0's resolved ServeContext —
        all replicas share it; keeps the CLI/demo code engine-agnostic)."""
        return self.replicas[0].serve

    # -- lifecycle ---------------------------------------------------------

    def start(self, warmup: bool = True) -> "PartitionFleet":
        """Start every replica.  With warmup, replica 0 pays the ladder
        precompile once; replicas 1..N-1 inherit its warm state (report,
        warm cells, lane-stack keys, EMA seed) and skip every inherited
        cell — the compile-count delta of an inheriting replica's start is
        asserted to be zero in tests/test_fleet.py."""
        with self._lock:
            if self._started:
                return self
            self._started = True
            self._stopping = False
            # Remembered for elastic scale-up: a spawned replica starts
            # the way the fleet itself was started.
            self._warmup_flag = bool(warmup)
        first = self.replicas[0]
        first.start(warmup=warmup)
        for eng in self.replicas[1:]:
            if warmup and self.fleet_ctx.inherit_warm_cache:
                eng.inherit_warmup(first)
            eng.start(warmup=warmup)
        return self

    def pause(self) -> None:
        for eng in self.replicas:
            eng.pause()

    def resume(self) -> None:
        for eng in self.replicas:
            eng.resume()

    def shutdown(self, drain: bool = True) -> None:
        """Stop every replica (bounded per-replica drain; a hung replica's
        in-flight work is force-resolved typed by the engine's bounded
        shutdown — the fleet does not resteer during its own shutdown)."""
        with self._lock:
            if not self._started:
                return
            self._stopping = True
            bg = self._bg_scale
        if bg is not None:
            bg.join(self.fleet_ctx.drain_timeout_s)
        for t in self._drain_threads:
            if t is not None:
                t.join(self.fleet_ctx.drain_timeout_s)
        for i, eng in enumerate(self.replicas):
            if not self._draining[i]:
                eng.shutdown(
                    drain=drain, timeout_s=self.fleet_ctx.drain_timeout_s
                )
        with self._lock:
            self._records.clear()
            self._started = False

    def __enter__(self) -> "PartitionFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- steering ----------------------------------------------------------

    def _service_floor(self, eng: PartitionEngine) -> float:
        ema = eng.stats_.service_time_estimate()
        return max(ema, self.fleet_ctx.steer_service_floor_s)

    def _replica_available(self, idx: int, probe_ok: bool = True,
                           consume: bool = True):
        """(available, is_probe) for replica ``idx``.  A closed fleet
        breaker on a non-draining replica is the normal case; an
        open/half-open breaker grants the single half-open probe slot
        (``probe_ok``), restarting a drained engine for it — probe
        replicas are routed FIRST by ``_pick_replica`` (a probe is
        traffic: consuming the slot without sending a request would
        leave the replica demoted for another cooldown).

        ``consume=False`` peeks: same decision, but the probe slot is
        not consumed and the replica not restored — the candidate scan
        peeks first so its cell-breaker/capacity filters cannot burn a
        probe on a replica they then drop."""
        if self._stopping:
            return False, False
        if self._retired[idx]:
            # Scaled down on purpose: not a health failure, so no probe
            # traffic — only scale_to revives a retired slot.
            return False, False
        br = self.breakers.get("replica", (idx,))
        if br.state == "closed":
            if self._draining[idx]:
                return False, False
            return self.replicas[idx].running, False
        t = self._drain_threads[idx]
        if t is not None and t.is_alive():
            # The drain is still in progress (bounded shutdown running):
            # do NOT consume the half-open probe slot for it — restoring
            # now would join the drain thread on the submit hot path,
            # stalling a caller for up to the drain budget.
            return False, False
        if not probe_ok:
            return False, False
        if not consume:
            return br.would_allow(), True
        if not br.allow():
            return False, False
        # Half-open probe granted: restore the replica for it.
        self._restore_replica(idx)
        with self._lock:
            self._counters["probe_steers"] += 1
        return True, True

    def _restore_replica(self, idx: int) -> None:
        """Restart a drained replica for a half-open probe (warm state —
        solver caches, warm cells, stats — carries over engine restarts)."""
        t = self._drain_threads[idx]
        if t is not None:
            t.join(self.fleet_ctx.drain_timeout_s)
            self._drain_threads[idx] = None
        eng = self.replicas[idx]
        if not eng.running:
            eng.start(warmup=False)
        if self._draining[idx]:
            self._draining[idx] = False
            with self._lock:
                self._counters["restores"] += 1

    def _score(self, idx: int, cell: ShapeCell) -> float:
        """SLO-aware steering score (lower = better).

        queue term: drain-time estimate of the replica's queued work
        (depth x unamortized EMA / batch width — the PR 6 rule keeps the
        EMA unamortized for lane-stacked batches, so depth/batch-width is
        the only occupancy division).  p99 term: tail execute latency.
        Batch-join bonus: a forming same-cell batch (0 < depth <
        max_batch) attracts the request so the lane axis fills before
        load spills to the next device.

        SLO pressure term (round 20, telemetry/slo.py): a replica burning
        its declared error budget (max(0, worst_burn - 1), in
        service-time units per unit of excess burn) looks slower to the
        router and sheds new load to healthier siblings.  0 whenever
        objectives are disarmed — a control input only, never a
        partition input."""
        eng = self.replicas[idx]
        sig = eng.steer_signals()
        per = self._service_floor(eng)
        max_batch = max(1, int(sig["max_batch"]))
        score = (
            self.fleet_ctx.steer_queue_weight
            * sig["queue_depth"] * per / max_batch
            + self.fleet_ctx.steer_p99_weight * sig["p99_execute_s"]
            + self.fleet_ctx.steer_slo_weight
            * sig.get("slo_pressure", 0.0) * per
        )
        cell_d = eng.cell_depth(cell)
        if 0 < cell_d < max_batch:
            score -= self.fleet_ctx.batch_join_bonus * per
        return score

    def _pick_replica(
        self, cell: ShapeCell, graph, k: int,
        exclude: Sequence[int] = (), meta: Optional[dict] = None,
    ) -> List[int]:
        """Candidate replica indices, best first.  Half-open probe
        replicas lead (a granted probe slot must carry this request or
        the replica stays demoted another cooldown).  Hard skips: an open
        cell breaker for THIS cell whose cooldown has not elapsed
        (poisoned there, maybe healthy elsewhere — once the cooldown
        passes the request routes through, so the ENGINE's admission
        ``allow()`` can grant the cell's own half-open probe), and a
        failing capacity-preflight verdict (per-replica ceilings
        differ).  ``meta`` reports considered/capacity-skip counts so
        the submit path can type an all-replicas-oversize rejection."""
        probes: List[int] = []
        scored = []
        considered = 0
        capacity_skips = 0
        cell_key = (cell.n_bucket, cell.m_bucket, cell.k)
        # One preflight per distinct (ceiling, device kind), not per
        # replica: a homogeneous fleet pays the host arithmetic once per
        # scan instead of N times (heterogeneous ceilings still each get
        # their own verdict).
        verdicts: Dict[tuple, bool] = {}
        for idx in range(len(self.replicas)):
            if idx in exclude:
                continue
            # Peek availability (no probe consumption): the filters
            # below may still drop this replica, and a consumed probe
            # that carries no request leaves it demoted another cooldown.
            ok, is_probe = self._replica_available(idx, consume=False)
            if not ok:
                continue
            considered += 1
            eng = self.replicas[idx]
            br = eng.breakers.get("cell", cell_key)
            if br.state != "closed" and br.retry_after_s() > 0.0:
                continue
            vkey = (eng._capacity_ceiling, eng._device_kind)
            verdict = verdicts.get(vkey)
            if verdict is None:
                verdict = verdicts[vkey] = eng.capacity_verdict(graph, k)
            if not verdict:
                capacity_skips += 1
                continue
            if is_probe:
                # The filters passed: consume the probe slot now (this
                # restores/restarts the replica) — a lost race on the
                # slot just drops the candidate.
                if self._replica_available(idx)[0]:
                    probes.append(idx)
            else:
                scored.append((self._score(idx, cell), idx))
        scored.sort()  # deterministic: (score, index)
        if meta is not None:
            meta["considered"] = considered
            meta["capacity_skips"] = capacity_skips
            # Per-replica score inputs for the request-trace steer event
            # (round 20): what the router saw when it ranked candidates.
            meta["probes"] = list(probes)
            meta["scores"] = [
                {"replica": idx, "score": round(score, 6)}
                for score, idx in scored
            ]
        return probes + [idx for _, idx in scored]

    def _check_auto_drain(self) -> None:
        """Lazily drain replicas whose watchdog fired or whose cell
        breakers latched open (the submit-path health check — no extra
        monitor thread; a fleet with no traffic has nothing to steer).
        Throttled to one sweep per ``_health_interval_s`` so a burst does
        not pay the per-replica signal reads per request."""
        now = time.monotonic()
        if now - self._last_health_check < self._health_interval_s:
            return
        self._last_health_check = now
        if self.fleet_ctx.auto_drain:
            # A health drain REPLACES the replica (round 19) when the
            # fleet is configured elastic: capacity must not dip for the
            # drain cooldown, so a fresh replica (inheriting the fleet's
            # warm state) takes the retired slot's place immediately.
            replace = (
                self.fleet_ctx.replace_drained or self.fleet_ctx.autoscale
            )
            for idx, eng in enumerate(self.replicas):
                if self._draining[idx] or not eng.running:
                    continue
                sig = eng.steer_signals()
                if sig["watchdog_timeouts"] < self._watchdog_seen[idx]:
                    # The engine's stats were reset under us (bench
                    # windows do): re-anchor the watermark or real fires
                    # after the reset would be silently swallowed by the
                    # stale delta.
                    self._watchdog_seen[idx] = sig["watchdog_timeouts"]
                fired = sig["watchdog_timeouts"] - self._watchdog_seen[idx]
                open_cells = sig["open_cell_breakers"]
                if fired > 0 or (
                    self.fleet_ctx.auto_drain_open_cells > 0
                    and open_cells >= self.fleet_ctx.auto_drain_open_cells
                ):
                    self._watchdog_seen[idx] = sig["watchdog_timeouts"]
                    reason = (
                        f"watchdog fired {fired}x" if fired > 0
                        else f"{open_cells} cell breakers latched open"
                    )
                    self.drain_replica(idx, reason=reason, retire=replace)
                    if replace:
                        self._replace_replica(idx, reason)
        self._autoscale_sweep()

    # -- elastic scaling (round 19, ISSUE 15) ------------------------------

    def _active_indices(self) -> List[int]:
        """Slots participating in the fleet's target size (everything not
        retired — a health-drained-but-not-retired replica still counts:
        it is expected back through the half-open probe)."""
        return [
            i for i in range(len(self.replicas)) if not self._retired[i]
        ]

    @property
    def active_replicas(self) -> int:
        return len(self._active_indices())

    def scale_to(self, n: int, reason: str = "") -> dict:
        """Elastically resize the fleet to ``n`` active replicas UNDER
        LIVE TRAFFIC (round 19 tentpole c).

        Scale-up first revives retired slots in index order (the engine
        object is kept across retirement, so its warm state — solver
        caches, warm cells, stats — carries over for free), then spawns
        fresh replicas that inherit the fleet's warm state + shared
        persistent cache dir (zero compile-event warmup delta, the PR 14
        inheritance argument) and journal nothing until started.
        Scale-down retires the highest-index active replicas through the
        PR 14 drain/resteer machinery — queued work requeues eagerly on
        the survivors, in-flight work finishes or is force-resolved typed
        and resteered lazily, so resolutions are conserved (zero lost,
        zero duplicated — asserted under an 8-thread live burst in
        tests/test_elastic.py) and sticky tenants re-home on their next
        request (counted in ``sticky_moves``).

        Returns an action summary ``{target, active, spawned, revived,
        retired}``.  Serialized against concurrent scaling; never goes
        below one active replica."""
        n = max(1, int(n))
        if not self._started or self._stopping:
            raise EngineStoppedError("fleet not started (call start())")
        with self._scale_lock:
            active = self._active_indices()
            delta = n - len(active)
            actions: dict = {
                "target": n, "spawned": [], "revived": [], "retired": [],
            }
            if delta > 0:
                with self._lock:
                    self._counters["fleet_scale_ups"] += 1
                for _ in range(delta):
                    revived = None
                    for i in range(len(self.replicas)):
                        if self._retired[i] and self._revive_replica(i):
                            revived = i
                            break
                    if revived is not None:
                        actions["revived"].append(revived)
                        with self._lock:
                            self._counters["fleet_scale_revives"] += 1
                    else:
                        # No retired slot (or every candidate's drain is
                        # still wedged in flight): spawn fresh.
                        actions["spawned"].append(self._spawn_replica())
                        with self._lock:
                            self._counters["fleet_scale_spawns"] += 1
            elif delta < 0:
                with self._lock:
                    self._counters["fleet_scale_downs"] += 1
                for idx in sorted(active, reverse=True)[:-delta]:
                    self.drain_replica(
                        idx,
                        reason=reason or f"scale_to({n})",
                        retire=True,
                    )
                    actions["retired"].append(idx)
                    with self._lock:
                        self._counters["fleet_scale_retires"] += 1
            actions["active"] = self.active_replicas
        from ..telemetry import trace as ttrace

        trec = ttrace.active()
        if trec is not None:
            trec.instant("fleet.scale", target=n, reason=reason,
                         spawned=len(actions["spawned"]),
                         revived=len(actions["revived"]),
                         retired=len(actions["retired"]))
        return actions

    def _spawn_replica(self) -> int:
        """Construct + start one fresh replica at the next index (caller
        holds ``_scale_lock``): same deepcopied base context, device
        placement wrapping the mesh, the fleet's shared persistent cache
        dir, and warm-state inheritance from the first healthy replica —
        it joins the routable set only once started (``running`` gates
        ``_replica_available``), and journals nothing until then."""
        idx = len(self.replicas)
        try:
            import jax

            n_dev = max(1, len(jax.devices()))
        except Exception:  # noqa: BLE001 — placement is locality only
            n_dev = 1
        rctx = copy.deepcopy(self.ctx)
        rctx.parallel.placement_device = idx % n_dev
        if self._cache_enabled and self._cache_dir:
            rctx.parallel.compilation_cache_dir = self._cache_dir
        if rctx.serve.journal_path:
            rctx.serve.journal_path += f".replica{idx}"
        eng = PartitionEngine(
            rctx, name=f"replica{idx}", **self._serve_overrides
        )
        # Fresh replicas join the fleet's shared request-trace registry
        # (round 20): a request resteered onto this replica extends its
        # original event chain.
        eng.reqtrace = self.reqtrace
        donor = next(
            (
                self.replicas[i] for i in self._active_indices()
                if self.replicas[i].running and not self._draining[i]
            ),
            None,
        )
        if donor is not None and self.fleet_ctx.inherit_warm_cache:
            eng.inherit_warmup(donor)
        eng.start(warmup=self._warmup_flag)
        # State arrays grow BEFORE the replicas list: every reader
        # indexes arrays by a position < len(self.replicas).
        with self._lock:
            self._draining.append(False)
            self._drain_threads.append(None)
            self._watchdog_seen.append(0)
            self._steered.append(0)
            self._retired.append(False)
        self.replicas.append(eng)
        return idx

    def _revive_replica(self, idx: int) -> bool:
        """Bring a retired slot back into rotation (caller holds
        ``_scale_lock``): join any straggling drain, restart the kept
        engine (warm state carries over restarts — no warmup pass), and
        administratively close its fleet breaker (the trip recorded an
        intentional retirement, not a health verdict).

        Returns False — slot NOT revived — when the drain thread is
        still alive after the join budget: its eventual ``shutdown``
        would stop the engine right after we marked it active, leaving a
        phantom slot that counts toward capacity but routes nothing.
        The caller spawns a fresh replica instead."""
        t = self._drain_threads[idx]
        if t is not None:
            t.join(self.fleet_ctx.drain_timeout_s)
            if t.is_alive():
                return False
            self._drain_threads[idx] = None
        eng = self.replicas[idx]
        if not eng.running:
            eng.start(warmup=False)
        with self._lock:
            self._retired[idx] = False
            self._draining[idx] = False
        self.breakers.get("replica", (idx,)).reset()
        return True

    def _scale_in_background(self, fn, label: str) -> None:
        """Run one sweep-triggered scaling action detached: replica
        spawn + warmup can take seconds, and the health sweep runs on a
        client's submit thread.  At most one action is in flight; extra
        triggers are absorbed (the next sweep re-evaluates the signal)."""
        with self._lock:
            if self._bg_scale is not None and self._bg_scale.is_alive():
                return
            thread = threading.Thread(
                target=self._run_bg_scale, args=(fn,),
                name=f"kaminpar-fleet-scale-{label}", daemon=True,
            )
            self._bg_scale = thread
        thread.start()

    def _run_bg_scale(self, fn) -> None:
        try:
            fn()
        except EngineStoppedError:
            pass  # fleet shut down under the action: nothing to scale
        except Exception as exc:  # noqa: BLE001 — a failed background
            # scale must be loud, not a silently dead thread.
            warnings.warn(
                f"kaminpar_tpu fleet: background scaling failed "
                f"({type(exc).__name__}: {exc})",
                RuntimeWarning,
                stacklevel=2,
            )

    def _replace_replica(self, idx: int, reason: str) -> None:
        """Health-sweep replacement: the watchdog/breaker drain retired
        slot ``idx``; spawn a FRESH substitute (never revive the sick
        slot — reviving would restart the engine the watchdog just
        condemned, while its drain is still in flight) so active
        capacity does not dip for the drain cooldown.  The spawn runs
        detached (see :meth:`_scale_in_background`)."""
        if self._stopping:
            return
        with self._lock:
            self._counters["fleet_scale_replacements"] += 1

        def _spawn():
            if self._stopping:
                return
            with self._scale_lock:
                self._spawn_replica()
                with self._lock:
                    self._counters["fleet_scale_spawns"] += 1

        self._scale_in_background(_spawn, f"replace{idx}")

    def _autoscale_sweep(self) -> None:
        """Watermark autoscaler (round 19): driven from the same
        submit-path health sweep as auto-drain — the mean per-replica
        queue-drain estimate (depth x unamortized EMA / max_batch, the
        PR 6 rule) crossing ``autoscale_high_s`` for
        ``autoscale_hysteresis`` CONSECUTIVE sweeps scales up one
        replica; staying under ``autoscale_low_s`` scales down one —
        never past the min/max bounds, and the counters reset whenever
        the signal leaves the band (hysteresis means sustained pressure,
        not one spike)."""
        fc = self.fleet_ctx
        if not fc.autoscale:
            return
        # RAW drain estimate (depth x unamortized EMA / max_batch), not
        # retry_after_estimate: that one floors at 0.05 s as an
        # anti-busy-spin backpressure hint, and a floor would read an
        # IDLE fleet as permanently above any smaller high watermark.
        estimates = []
        pressures = []
        for idx, eng in enumerate(self.replicas):
            if (
                self._draining[idx] or self._retired[idx]
                or not eng.running
            ):
                continue
            estimates.append(
                len(eng._queue)
                * eng.stats_.service_time_estimate()
                / max(1, eng.serve.max_batch)
            )
            pressures.append(
                eng._slo.pressure() if eng._slo is not None else 0.0
            )
        if not estimates:
            return
        mean = sum(estimates) / len(estimates)
        # SLO pressure boost (round 20, telemetry/slo.py): sustained
        # error-budget burn reads as extra seconds on the drain estimate
        # (autoscale_slo_boost seconds per unit of mean excess burn), so
        # a fleet missing its objectives scales up before raw queue depth
        # alone crosses the watermark.  0 whenever objectives are
        # disarmed — the watermark arithmetic is then unchanged.
        mean += (
            fc.autoscale_slo_boost * sum(pressures) / len(pressures)
        )
        active = len(self._active_indices())
        hysteresis = max(1, int(fc.autoscale_hysteresis))
        if mean > fc.autoscale_high_s and active < fc.autoscale_max_replicas:
            self._above_high += 1
            self._below_low = 0
            if self._above_high >= hysteresis:
                self._above_high = 0
                with self._lock:
                    self._counters["fleet_scale_auto_ups"] += 1
                reason = (f"autoscale: drain estimate {mean:.3f}s > "
                          f"{fc.autoscale_high_s}s")
                # Detached: a scale-up may spawn + warm a replica.
                self._scale_in_background(
                    lambda n=active + 1, r=reason: self.scale_to(n, r),
                    "auto-up",
                )
        elif mean < fc.autoscale_low_s and active > fc.autoscale_min_replicas:
            self._below_low += 1
            self._above_high = 0
            if self._below_low >= hysteresis:
                self._below_low = 0
                with self._lock:
                    self._counters["fleet_scale_auto_downs"] += 1
                reason = (f"autoscale: drain estimate {mean:.3f}s < "
                          f"{fc.autoscale_low_s}s")
                self._scale_in_background(
                    lambda n=active - 1, r=reason: self.scale_to(n, r),
                    "auto-down",
                )
        else:
            self._above_high = 0
            self._below_low = 0

    # -- request path ------------------------------------------------------

    def submit(
        self,
        graph,
        k: int,
        epsilon: float = 0.03,
        *,
        graph_id=None,
        replica: Optional[int] = None,
        **request_kwargs,
    ) -> FleetFuture:
        """Steer one request to a replica and enqueue it there.

        ``graph_id``: opaque tenant/graph key for sticky routing — repeat
        ids keep landing on their warm replica while it stays healthy.
        ``replica``: explicit pin (tests/operations), bypassing scoring.
        Raises :class:`QueueFullError` when every routable replica's queue
        is full — ``retry_after_s`` is the LEAST-LOADED replica's drain
        estimate (not the rejecting replica's EMA), and
        :class:`EngineStoppedError` when the fleet is not running."""
        if not self._started or self._stopping:
            raise EngineStoppedError("fleet not started (call start())")
        from ..telemetry import trace as ttrace
        from ..utils.timer import scoped_timer

        cell = shape_cell(graph, k)
        with scoped_timer("fleet_steer"):
            self._check_auto_drain()
            self._prune_records()
            with self._lock:
                self._counters["submitted"] += 1
            home = None
            if (
                replica is None and graph_id is not None
                and self.fleet_ctx.sticky_routing
            ):
                with self._lock:
                    home = self._sticky.get(graph_id)
                    if home is not None:
                        # LRU refresh: a live tenant's binding must not
                        # be the eviction victim.
                        self._sticky[graph_id] = self._sticky.pop(graph_id)
                if home is not None and not self._replica_available(
                    home, probe_ok=False
                )[0]:
                    home = None  # sticky replica drained: steer fresh
            meta: dict = {}
            if replica is not None:
                candidates = [int(replica)]
            elif home is not None:
                # Sticky preference, not a hard pin: a full warm replica
                # falls back to normal steering (locality optimization,
                # never an availability constraint).
                candidates = [home] + self._pick_replica(
                    cell, graph, k, exclude=(home,), meta=meta
                )
            else:
                candidates = self._pick_replica(cell, graph, k, meta=meta)
            if not candidates and replica is None:
                if meta.get("considered") and (
                    meta["capacity_skips"] == meta["considered"]
                ):
                    # Every routable replica's ceiling rejects this
                    # request: that is a deterministic oversize, not
                    # backpressure — surface the TYPED CapacityError
                    # (with its prediction payload) via the counting
                    # engine path instead of a retry-forever hint.
                    with self._lock:
                        self._counters["rejected_capacity"] += 1
                    for idx in range(len(self.replicas)):
                        if self._replica_available(idx, probe_ok=False)[0]:
                            self.replicas[idx]._capacity_preflight(graph, k)
                self._unroutable(cell)
            rec_id = next(self._ids)
            record = _FleetRecord(
                rec_id, graph, k, epsilon, request_kwargs, graph_id,
                trace_id=self.reqtrace.mint(),
            )
            self.reqtrace.bind_fleet(rec_id, record.trace_id)
            # Steer-decision trace event (round 20): the candidate ranking
            # and per-replica score inputs the router saw BEFORE the
            # admission attempts — the engine's admit event that follows
            # names the replica that actually took the request.
            self.reqtrace.record(
                record.trace_id, "steer", fleet_id=rec_id, k=int(k),
                n_bucket=cell.n_bucket, m_bucket=cell.m_bucket,
                candidates=list(candidates),
                sticky_home=(-1 if home is None else int(home)),
                pinned=(-1 if replica is None else int(replica)),
                considered=meta.get("considered", 0),
                capacity_skips=meta.get("capacity_skips", 0),
                probes=meta.get("probes", []),
                scores=meta.get("scores", []),
            )
            fut = self._submit_record(record, candidates, cell, graph, k)
        sticky_used = home is not None and record.replica == home
        if sticky_used:
            with self._lock:
                self._counters["sticky_hits"] += 1
        elif graph_id is not None and self.fleet_ctx.sticky_routing:
            with self._lock:
                moved = (
                    self._sticky.get(graph_id) not in (None, record.replica)
                )
                self._sticky_bind_locked(graph_id, record.replica)
                if moved:
                    self._counters["sticky_moves"] += 1
        rec = ttrace.active()
        if rec is not None:
            rec.instant(
                "fleet.steer", fleet_id=record.fleet_id,
                replica=record.replica, k=int(k),
                n_bucket=cell.n_bucket, m_bucket=cell.m_bucket,
                sticky=sticky_used,
            )
        return fut

    def _submit_record(
        self, record: _FleetRecord, candidates: List[int],
        cell: ShapeCell, graph, k: int,
    ) -> FleetFuture:
        """Try candidates best-first; a per-replica QueueFullError,
        PoisonedCell or CapacityError moves on to the next (counted as a
        steer retry) — sticky/pinned candidates bypass the scan's
        capacity filter, so a request oversize for its home replica must
        still reach a sibling with a larger ceiling."""
        from ..resilience.errors import PoisonedCell
        from .errors import CapacityError

        last_exc: Optional[BaseException] = None
        last_capacity: Optional[CapacityError] = None
        for idx in candidates:
            eng = self.replicas[idx]
            try:
                fut = eng.submit(
                    record.graph, record.k, record.epsilon,
                    trace_id=record.trace_id, **record.kwargs
                )
            except CapacityError as exc:
                last_capacity = exc
                with self._lock:
                    self._counters["steer_retries"] += 1
                continue
            except (QueueFullError, PoisonedCell, EngineStoppedError) as exc:
                last_exc = exc
                with self._lock:
                    self._counters["steer_retries"] += 1
                continue
            record.replica = idx
            record.current = fut
            with self._lock:
                self._steered[idx] += 1
                self._records[id(fut)] = record
            return FleetFuture(self, record)
        if isinstance(last_exc, QueueFullError):
            with self._lock:
                self._counters["rejected_full"] += 1
            raise QueueFullError(self._fleet_retry_after()) from None
        if last_capacity is not None:
            # Every tried replica rejected on capacity (and none on
            # backpressure): a deterministic oversize — surface the TYPED
            # error with its prediction payload, not a retry hint.
            with self._lock:
                self._counters["rejected_capacity"] += 1
            raise last_capacity
        self._unroutable(cell, last_exc)

    def _unroutable(self, cell: ShapeCell, cause=None):
        """No replica can take this request right now: reject with the
        fleet-wide retry hint (a draining fleet recovers; callers back
        off rather than error out)."""
        with self._lock:
            self._counters["rejected_unroutable"] += 1
        retry = self._fleet_retry_after()
        for idx in range(len(self.replicas)):
            br = self.breakers.get("replica", (idx,))
            if br.state != "closed":
                retry = max(retry, br.retry_after_s())
        raise QueueFullError(retry) from cause

    def _fleet_retry_after(self) -> float:
        """Backpressure hint on a fleet-level reject: the LEAST-LOADED
        routable replica's drain estimate — depth x unamortized EMA /
        batch width (ISSUE 14 satellite; the rejecting replica's own EMA
        can be arbitrarily pessimistic while a sibling is nearly idle).
        Falls back to the global floor when nothing is routable."""
        estimates = [
            eng.stats_.retry_after_estimate(
                len(eng._queue), eng.serve.max_batch
            )
            for idx, eng in enumerate(self.replicas)
            if not self._draining[idx] and eng.running
        ]
        return min(estimates) if estimates else 0.1

    def partition(self, graph, k: int, epsilon: float = 0.03, **kw):
        """Synchronous convenience wrapper: submit + wait, returning the
        (n,) block array."""
        return self.submit(graph, k, epsilon, **kw).result().partition

    # -- drain + cross-replica resteer -------------------------------------

    def drain_replica(self, idx: int, reason: str = "",
                      retire: bool = False) -> None:
        """Take replica ``idx`` out of rotation: trip its fleet breaker,
        requeue its queued work on healthy replicas eagerly, then shut it
        down with the bounded drain (in-flight work finishes normally, or
        a hung dispatcher's futures are force-resolved typed and resteered
        lazily by their waiters).  Zero lost, zero duplicated resolutions
        — asserted under concurrent overload in tests/test_fleet.py.

        ``retire`` (round 19): additionally mark the slot retired —
        ``scale_to`` scale-downs and health-sweep *replacements* use it;
        a retired slot is never probe-restored, only revived by a later
        scale-up."""
        idx = int(idx)
        with self._lock:
            already = self._draining[idx]
            self._draining[idx] = True
            if retire:
                self._retired[idx] = True
            if not already:
                self._counters["drains"] += 1
        if already:
            return
        eng = self.replicas[idx]
        self.breakers.get("replica", (idx,)).trip()
        self.breakers.record_demotion(
            "replica", reason or "drained", warn=True
        )
        from ..telemetry import trace as ttrace

        trec = ttrace.active()
        if trec is not None:
            trec.instant("fleet.drain", replica=idx, reason=reason,
                         queued=len(eng._queue))

        def _drain():
            # Eager leg: everything still queued (never started) moves
            # NOW — requeues honor sibling backpressure (bounded
            # retry-after waits inside _resteer), so a momentarily full
            # fleet loses nothing.
            for req in eng._queue.drain_items():
                with self._lock:
                    record = self._records.pop(id(req.future), None)
                if record is not None and self._resteer(record, req.future):
                    # Re-homed: resolve the entry in THIS replica's
                    # journal (round 19) — the sibling's journal owns the
                    # work now, and an unresolved entry here would replay
                    # already-completed work if the slot is later revived.
                    eng.journal_mark_resteered(req.id)
                # Resolve the abandoned engine future LAST: a waiter
                # waking on it re-reads record.current, which already
                # points elsewhere (or surfaces the typed error if the
                # resteer failed for good).
                req.future._reject(EngineStoppedError(
                    f"replica {idx} drained"
                    + (f": {reason}" if reason else "")
                ))
            # Bounded-drain leg: in-flight work finishes normally; a hung
            # dispatcher's futures are force-resolved typed (WorkerHung)
            # by the engine and resteered lazily by their waiters.
            try:
                eng.shutdown(
                    drain=True, timeout_s=self.fleet_ctx.drain_timeout_s
                )
            except Exception as exc:  # noqa: BLE001 — a failing drain must
                # not kill the drain thread silently; surface and carry on
                # (the replica breaker is already open).
                warnings.warn(
                    f"kaminpar_tpu fleet: draining replica {idx} failed "
                    f"({type(exc).__name__}: {exc}); its breaker stays "
                    "open until the half-open probe.",
                    RuntimeWarning,
                    stacklevel=2,
                )

        # The whole drain runs detached: the submit-path auto-drain check
        # (and operators) must never block on a replica's drain budget.
        t = threading.Thread(
            target=_drain, name=f"kaminpar-fleet-drain-{idx}", daemon=True
        )
        self._drain_threads[idx] = t
        t.start()

    def _maybe_resteer(
        self, record: _FleetRecord, failed: ServeFuture, exc: BaseException
    ) -> bool:
        """Waiter-side resteer hook: rebind a request its replica gave
        back (resteerable typed errors only).  Returns True when the
        waiter should re-wait on the (possibly already rebound) binding."""
        with record.lock:
            if record.current is not failed:
                return True  # the eager drain leg already rebound it
        if not _is_resteerable(exc):
            return False
        if record.replica >= 0:
            # The replica failed this request's dispatch: feed the fleet
            # breaker (a hung replica trips toward drain even when the
            # auto-drain check has not run yet).
            self.breakers.get("replica", (record.replica,)).record_failure()
        return self._resteer(record, failed)

    def _resteer(
        self, record: _FleetRecord, failed: ServeFuture
    ) -> bool:
        """Cross-replica requeue (idempotent per failed binding): submit
        the request on the best healthy replica excluding the failed one,
        swap the binding, and count it.  Sibling backpressure
        (QueueFullError) is waited out with bounded retry-after sleeps up
        to the drain budget — a momentarily saturated fleet must not LOSE
        a drained replica's work.  False = no resteer budget, the fleet is
        stopping, or every path stayed closed (the caller surfaces the
        typed error)."""
        if self._stopping:
            return False
        with record.lock:
            if record.current is not failed:
                return True  # lost the race to another resteer path
            if record.attempts >= self.fleet_ctx.max_resteers:
                return False
            from ..resilience.errors import PoisonedCell

            cell = shape_cell(record.graph, record.k)
            exclude = (record.replica,) if record.replica >= 0 else ()
            deadline = time.monotonic() + self.fleet_ctx.drain_timeout_s
            while True:
                backpressure: Optional[QueueFullError] = None
                for idx in self._pick_replica(
                    cell, record.graph, record.k, exclude=exclude,
                ):
                    try:
                        fut = self.replicas[idx].submit(
                            record.graph, record.k, record.epsilon,
                            trace_id=record.trace_id, **record.kwargs,
                        )
                    except QueueFullError as exc:
                        backpressure = exc
                        continue
                    except (PoisonedCell, EngineStoppedError):
                        continue
                    old = record.current
                    # Resteer-hop trace event (round 20): which replica
                    # gave the request back and where it re-homed — the
                    # new replica's admit event (same trace id) follows.
                    self.reqtrace.record(
                        record.trace_id, "resteer",
                        fleet_id=record.fleet_id,
                        from_replica=int(record.replica), replica=int(idx),
                        attempt=record.attempts + 1,
                    )
                    record.replica = idx
                    record.current = fut
                    record.attempts += 1
                    with self._lock:
                        self._records.pop(id(old), None)
                        self._records[id(fut)] = record
                        self._counters["resteers"] += 1
                        self._steered[idx] += 1
                        if (
                            record.graph_id is not None
                            and self.fleet_ctx.sticky_routing
                        ):
                            self._sticky_bind_locked(record.graph_id, idx)
                            self._counters["sticky_moves"] += 1
                    return True
                if (
                    backpressure is None  # nothing routable at any load
                    or self._stopping
                    or time.monotonic() >= deadline
                ):
                    return False
                time.sleep(min(backpressure.retry_after_s, 0.25))

    def _note_success(self, record: _FleetRecord) -> None:
        """A fleet-routed request completed on its replica: close the
        replica's fleet breaker (restoring a half-open probe).

        A success delivered by a DRAINING replica (in-flight work
        finishing inside the bounded drain) must NOT close its tripped
        breaker: a closed breaker on a draining replica is unroutable
        forever — only the half-open probe path clears ``_draining``."""
        if record.replica >= 0 and not self._draining[record.replica]:
            br = self.breakers.get("replica", (record.replica,))
            if br.record_success():
                self.breakers.record_restoration("replica")

    def _sticky_bind_locked(self, graph_id, idx: int) -> None:
        """Insert/refresh one sticky binding (caller holds ``_lock``),
        evicting least-recently-used bindings past the watermark — an
        evicted tenant just re-steers fresh on its next request."""
        self._sticky.pop(graph_id, None)
        self._sticky[graph_id] = idx
        while len(self._sticky) > self._sticky_watermark:
            self._sticky.pop(next(iter(self._sticky)))

    def _forget(self, record: _FleetRecord) -> None:
        fut = record.current  # lock-free: see FleetFuture.cancel()
        with self._lock:
            if fut is not None:
                self._records.pop(id(fut), None)

    def _prune_records(self) -> None:
        """Drop drain-lookup entries of DONE engine futures whose waiter
        never came back (timed-out or fire-and-forget callers) — the map
        would otherwise grow unboundedly, pinning every such request's
        graph.  Safe: the map is only the drain's queued-work lookup
        (done futures are past it) and ``_forget``'s target; a late
        waiter still holds the record object itself and resolves from
        the bound future."""
        with self._lock:
            if len(self._records) <= self._prune_watermark:
                return
            for key in [
                key for key, rec in self._records.items()
                if rec.current is not None and rec.current.done()
            ]:
                del self._records[key]

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Fleet-level snapshot: router counters, per-replica serving
        signals + occupancy, the aggregate lane x device occupancy, and
        the fleet-scoped breaker registry."""
        with self._lock:
            counters = dict(self._counters)
            steered = list(self._steered)
            draining = list(self._draining)
            retired = list(self._retired)
        per_replica = []
        agg_lanes = 0
        agg_occupancy = 0.0
        for idx, eng in enumerate(self.replicas):
            snap = eng.stats_.snapshot(queue_depth=len(eng._queue))
            cells = eng.warmup_cell_counts()
            per_replica.append({
                "replica": idx,
                "running": eng.running,
                "draining": draining[idx],
                "retired": retired[idx],
                "steered": steered[idx],
                "queue_depth": snap["queue_depth"],
                "completed": snap["completed"],
                "failed": snap["failed"],
                "batches": snap["batches"],
                "batch_occupancy_mean": snap["batch_occupancy_mean"],
                "batch_occupancy_max": snap["batch_occupancy_max"],
                "lanestacked_batches": snap["lanestacked_batches"],
                "lanestacked_lanes": snap["lanestacked_lanes"],
                "p99_execute_ms": snap["latency_ms"]["execute_ms"].get(
                    "p99", 0.0
                ),
                "ema_service_s": snap["ema_service_s"],
                "warmup_inherited_cells": cells["inherited"],
                "warmup_local_cells": cells["local"],
                "slo_pressure": (
                    eng._slo.pressure() if eng._slo is not None else 0.0
                ),
            })
            agg_lanes += snap["lanestacked_lanes"]
            agg_occupancy += snap["batch_occupancy_max"]
        return {
            "replicas": len(self.replicas),
            "active_replicas": len(self.replicas) - sum(retired),
            "running": self._started,
            **counters,
            "per_replica": per_replica,
            # Peak concurrent lane x device occupancy: the sum over
            # replicas of the widest batch each dispatched (8 replicas x
            # 8 lanes = 64, the ROADMAP "millions of users" figure).  On
            # the CPU dryrun this is an occupancy claim, not a speedup
            # claim (virtual devices serialize; TPU_NOTES round 18).
            "aggregate_occupancy": agg_occupancy,
            "aggregate_lanestacked_lanes": agg_lanes,
            # Worst replica SLO pressure (round 20): the autoscale boost
            # uses the mean; the dashboard headline wants the worst.
            "slo_pressure": max(
                (r["slo_pressure"] for r in per_replica), default=0.0
            ),
            "reqtrace": self.reqtrace.snapshot(),
            "breakers": self.breakers.snapshot(),
        }

    def explain(self, request) -> Optional[dict]:
        """Structured request dossier by :class:`FleetFuture` (or fleet
        id, or raw trace id): the whole cross-replica event chain — steer
        decision with score inputs, per-replica admits/dispatches,
        resteer hops, journal replays, resolution — with a connectivity
        verdict (telemetry/reqtrace.py).  ``None`` for unknown/evicted
        requests."""
        from ..utils.timer import scoped_timer

        with scoped_timer("reqtrace_export"):
            if isinstance(request, FleetFuture):
                return self.reqtrace.explain_fleet(request.fleet_id)
            if isinstance(request, str):
                return self.reqtrace.dossier(request)
            return self.reqtrace.explain_fleet(int(request))

    def metrics_text(self) -> str:
        """Prometheus text exposition of the fleet router (per-replica
        engine expositions stay available via each replica's
        ``metrics_text``; the fleet adds the routing layer)."""
        from ..resilience import breakers as rbreakers
        from ..telemetry import prometheus

        snap = self.stats()
        steer_samples = [
            ({"replica": str(r["replica"])}, r["steered"])
            for r in snap["per_replica"]
        ]
        depth_samples = [
            ({"replica": str(r["replica"])}, r["queue_depth"])
            for r in snap["per_replica"]
        ]
        inherit_samples = []
        for r in snap["per_replica"]:
            lbl = {"replica": str(r["replica"])}
            inherit_samples.append(
                ({**lbl, "source": "inherited"}, r["warmup_inherited_cells"])
            )
            inherit_samples.append(
                ({**lbl, "source": "local"}, r["warmup_local_cells"])
            )
        families = [
            ("kaminpar_fleet_replicas", "gauge",
             "Engine replicas owned by the fleet router",
             [({}, snap["replicas"])]),
            ("kaminpar_fleet_replicas_draining", "gauge",
             "Replicas currently drained out of rotation",
             [({}, sum(1 for r in snap["per_replica"] if r["draining"]))]),
            ("kaminpar_fleet_steered_total", "counter",
             "Requests steered per replica (SLO-aware scoring)",
             steer_samples),
            ("kaminpar_fleet_queue_depth", "gauge",
             "Per-replica bounded-queue depth",
             depth_samples),
            ("kaminpar_fleet_requests_total", "counter",
             "Fleet-level request outcomes at the router",
             [({"outcome": "submitted"}, snap["submitted"]),
              ({"outcome": "rejected_full"}, snap["rejected_full"]),
              ({"outcome": "rejected_unroutable"},
               snap["rejected_unroutable"]),
              ({"outcome": "rejected_capacity"},
               snap["rejected_capacity"])]),
            ("kaminpar_fleet_resteers_total", "counter",
             "Cross-replica requeues of work a draining/hung replica "
             "gave back (zero lost/duplicated resolutions)",
             [({}, snap["resteers"])]),
            ("kaminpar_fleet_sticky_total", "counter",
             "Graph-id-sticky routing decisions",
             [({"result": "hit"}, snap["sticky_hits"]),
              ({"result": "moved"}, snap["sticky_moves"])]),
            ("kaminpar_fleet_drains_total", "counter",
             "Replicas drained out of rotation (watchdog/breaker health)",
             [({}, snap["drains"])]),
            ("kaminpar_fleet_restores_total", "counter",
             "Drained replicas restored by the half-open probe",
             [({}, snap["restores"])]),
            ("kaminpar_fleet_active_replicas", "gauge",
             "Replicas participating in the fleet's elastic target size "
             "(total minus retired slots)",
             [({}, snap["active_replicas"])]),
            ("kaminpar_fleet_scale_total", "counter",
             "Elastic scaling events (round 19): scale_to calls by "
             "direction, slot transitions (spawn/revive/retire), "
             "health-sweep replacements, autoscale decisions",
             [({"op": "up"}, snap["fleet_scale_ups"]),
              ({"op": "down"}, snap["fleet_scale_downs"]),
              ({"op": "spawn"}, snap["fleet_scale_spawns"]),
              ({"op": "revive"}, snap["fleet_scale_revives"]),
              ({"op": "retire"}, snap["fleet_scale_retires"]),
              ({"op": "replacement"}, snap["fleet_scale_replacements"]),
              ({"op": "auto_up"}, snap["fleet_scale_auto_ups"]),
              ({"op": "auto_down"}, snap["fleet_scale_auto_downs"])]),
            ("kaminpar_fleet_warmup_cells_total", "counter",
             "Per-replica warmup cells by source: inherited from the "
             "fleet's warm state vs locally traced/compiled",
             inherit_samples or [({}, 0)]),
            ("kaminpar_fleet_aggregate_occupancy", "gauge",
             "Sum over replicas of the widest dispatched batch — the "
             "lane x device occupancy figure (device claim on real "
             "meshes; virtual CPU devices serialize)",
             [({}, snap["aggregate_occupancy"])]),
            ("kaminpar_slo_fleet_pressure", "gauge",
             "Worst per-replica SLO error-budget pressure "
             "(max(0, worst_burn - 1); 0 unless objectives are armed)",
             [({}, snap["slo_pressure"])]),
        ]
        families.extend(rbreakers.prometheus_families(self.breakers))
        return prometheus.render(families)
