"""Lane-stacked serve execution: one vmapped multilevel run per micro-batch.

PR 3's engine micro-batches same-shape-cell requests but executes the
pipeline once per graph; PR 4 built the per-lane RNG substrate.  This module
closes the loop (ISSUE 6): the padded CSR buffers of a whole shape-cell
batch are stacked along a leading lane axis and coarsening → initial
bipartitioning → uncoarsen/refine runs in *lockstep* — every device step is
ONE vmapped program over the stack (ops/lanestack.py) and every per-level
scalar readback is ONE stacked pull for all lanes (lane-accounted in
utils/sync_stats).

**Bit-identity** with sequential ``KaMinPar.compute_partition`` is the hard
contract (tests/test_lanestack.py asserts it across families, buckets, k and
lane counts).  It is engineered, not hoped for:

- every lane owns a :class:`LaneChain` — the exact key chain
  ``utils.rng.RandomState`` would thread through the lane's own sequential
  run (same seed, same split order) — and lockstep steps draw each lane's
  keys from its own chain exactly when the sequential code would (lanes
  whose balancer/coarsening exited early stop drawing, so chains never
  skew);
- host-orchestrated phases that the reference also runs sequentially
  (initial bipartitioning, extension) run *per lane* through the very same
  code paths, with the lane's chain swapped into the thread-local
  ``RandomState`` (:func:`lane_rng`);
- lanes share a stacked dispatch ONLY while their exact kernel shape
  signatures match (padded buckets, bucketed width classes + row pads,
  heavy pads, cur_k): jax's counter-based PRNG is positionally stable only
  at equal draw shapes, so the runner groups lanes by signature and splits
  cohorts when hierarchies diverge (``split`` events are counted in the
  runner's stats) — within a group, ``vmap`` runs literally the sequential
  per-lane computation;
- lanes whose coarsening converges at a different level peel off into
  their own cohort and the remaining lanes continue — the per-lane
  early-exit masking of the ISSUE.

Eligibility is an explicit envelope (:func:`check_eligibility`): the deep
mode with LP coarsening and the (overload-balancer, LP[, underload]) refiner
chain on int32 uniform-edge-weight graphs — the serve preset's
configuration.  Ineligible batches raise :class:`LaneStackUnsupported` and
the engine falls back to the per-graph loop, loudly and counted.
"""

from __future__ import annotations

import copy
import dataclasses
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..context import Context, PartitioningMode, RefinementAlgorithm
from ..graph.bucketed import host_deg_histogram
from ..graph.csr import CSRGraph, PaddedView, _next_bucket
from ..graph.isolated import assign_isolated_nodes, strip_isolated_csr
from ..initial.bipartitioner import HostCSR, recursive_bipartition
from ..ops import lanestack as lops
from ..ops.lp import num_labels_bucket
from ..partitioning.partition_utils import (
    compute_k_for_n,
    intermediate_block_weights,
)
from ..telemetry import probes
from ..utils import RandomState, sync_stats
from ..utils import rng
from ..utils.platform import host_pool_workers
from ..utils.timer import scoped_timer


class LaneStackUnsupported(Exception):
    """Batch/config outside the lane-stack envelope; the engine falls back
    to the per-graph loop (counted + warned)."""


# ---------------------------------------------------------------------------
# Per-lane RNG chains — RandomState's exact key arithmetic, one per lane.
# ---------------------------------------------------------------------------


class LaneChain:
    """The key chain ``RandomState`` threads through one sequential run:
    ``reseed(seed)`` then repeated ``split``.  Draw-for-draw identical to
    the facade's chain because it performs the same jax.random ops in the
    same order."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.key = rng.seed_key(seed)

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


@contextmanager
def lane_rng(chain: LaneChain):
    """Swap a lane's chain into the thread-local ``RandomState`` so
    unmodified sequential code (recursive_bipartition, extend_partition and
    everything below them) draws from the lane's stream; the advanced chain
    is read back on exit and the caller's stream is restored untouched."""
    tls = RandomState._tls
    prev_key = getattr(tls, "key", None)
    prev_seed = getattr(tls, "seed", None)
    tls.key = chain.key
    tls.seed = chain.seed
    try:
        yield
    finally:
        chain.key = tls.key
        tls.key = prev_key
        tls.seed = prev_seed


# ---------------------------------------------------------------------------
# Eligibility
# ---------------------------------------------------------------------------

_REFINER_CHAINS = (
    (RefinementAlgorithm.OVERLOAD_BALANCER, RefinementAlgorithm.LP),
    (
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.LP,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
    ),
)


def check_eligibility(ctx: Context, graphs: Sequence, k: int) -> None:
    """Raise :class:`LaneStackUnsupported` unless the batch fits the
    lockstep envelope (the serve preset's pipeline shape)."""

    def bail(reason: str):
        raise LaneStackUnsupported(reason)

    from ..context import ClusteringAlgorithm
    from ..ops.pallas_lp import resolve_lp_kernel

    if ctx.mode != PartitioningMode.DEEP:
        bail(f"mode {ctx.mode.value!r} (deep only)")
    if ctx.vcycles or ctx.restrict_vcycle_refinement:
        bail("v-cycle configuration")
    if ctx.compression.enabled:
        bail("compressed inputs")
    if ctx.use_64bit_ids:
        bail("64-bit id build")
    if ctx.coarsening.algorithm != ClusteringAlgorithm.LP:
        bail(f"coarsening algorithm {ctx.coarsening.algorithm.value!r}")
    if ctx.coarsening.overlay_levels > 1:
        bail("overlay clustering")
    if ctx.coarsening.sparsification.enabled:
        bail("sparsification")
    if resolve_lp_kernel(ctx.coarsening.lp.lp_kernel) != "xla":
        bail("pallas coarsening LP kernel")
    if resolve_lp_kernel(ctx.refinement.lp.lp_kernel) != "xla":
        bail("pallas refinement LP kernel")
    if ctx.coarsening.lp.weighted_mode is not None:
        bail("explicit weighted-mode pin (auto-detection only)")
    if tuple(ctx.refinement.algorithms) not in _REFINER_CHAINS:
        bail(f"refiner chain {tuple(a.value for a in ctx.refinement.algorithms)}")
    if ctx.initial_partitioning.device_extension:
        bail("device extension")
    if ctx.parallel.mesh_shape:
        bail("distributed mesh")
    if k < 2:
        bail("k < 2")
    for g in graphs:
        if g is None or getattr(g, "n", 0) <= 0:
            bail("empty graph")
        # .dtype reads without materializing a device array on the host.
        if g.row_ptr.dtype != np.int32:
            bail("non-int32 graph")
        if k > g.n:
            bail("k exceeds n")


# ---------------------------------------------------------------------------
# Per-lane facade state + stacked level state
# ---------------------------------------------------------------------------


@dataclass
class _Lane:
    slot: int                    # position in the request batch
    graph: object                # original CSRGraph
    chain: LaneChain
    ctx: Context                 # shallow per-lane ctx (own partition tree)
    caps: np.ndarray             # final (k,) max block weights, int64
    # isolated-node strip state (kaminpar.py facade replica)
    keep: Optional[np.ndarray]
    isolated: Optional[np.ndarray]
    work_host: Dict[str, np.ndarray]  # row_ptr/col_idx/node_w/edge_w of work graph
    work_n: int
    work_m: int
    tnw: int                     # work-graph total node weight
    # The facade's auto-detected weighted clustering mode (non-uniform edge
    # weights on the *input* graph); a per-lane STATIC of the clustering
    # kernel, so cohorts group by it.
    weighted: bool = False
    part: Optional[np.ndarray] = None  # final full-graph partition


@dataclass
class _Level:
    """One stacked hierarchy level of a cohort (lane axis leads)."""

    row_ptr: object              # (L, n_pad + 1)
    col_idx: object              # (L, m_pad)
    node_w: object               # (L, n_pad)
    edge_w: object               # (L, m_pad)
    edge_u: object               # (L, m_pad)
    n: np.ndarray                # (L,) real node counts
    m: np.ndarray                # (L,) real edge counts
    hist: List[np.ndarray]       # per-lane (12,) degree histograms
    max_nw: np.ndarray           # (L,) max node weights (refine relax)
    coarse_of: object = None     # (L, n_pad_fine) projection map (None at finest)
    layout: object = None        # cached (buckets, heavy, gather_idx)

    @property
    def n_pad(self) -> int:
        return int(self.row_ptr.shape[1]) - 1

    @property
    def m_pad(self) -> int:
        return int(self.col_idx.shape[1])

    def select(self, idx: List[int]) -> "_Level":
        take = jnp.asarray(idx)
        return _Level(
            row_ptr=jnp.take(self.row_ptr, take, axis=0),
            col_idx=jnp.take(self.col_idx, take, axis=0),
            node_w=jnp.take(self.node_w, take, axis=0),
            edge_w=jnp.take(self.edge_w, take, axis=0),
            edge_u=jnp.take(self.edge_u, take, axis=0),
            n=self.n[idx],
            m=self.m[idx],
            hist=[self.hist[i] for i in idx],
            max_nw=self.max_nw[idx],
            coarse_of=(
                None if self.coarse_of is None
                else jnp.take(self.coarse_of, take, axis=0)
            ),
            layout=None,  # rebuilt (cheap) for the subset
        )


@dataclass
class _Cohort:
    """Lanes advancing in lockstep over a shared stacked hierarchy."""

    lanes: List[_Lane]
    levels: List[_Level] = field(default_factory=list)

    @property
    def L(self) -> int:
        return len(self.lanes)

    def select(self, idx: List[int]) -> "_Cohort":
        return _Cohort(
            lanes=[self.lanes[i] for i in idx],
            levels=[lvl.select(idx) for lvl in self.levels],
        )


def _map_lanes(fn, L: int, pool=None, disable_timers: bool = False) -> list:
    """Run ``fn(i)`` for each lane on a host thread pool — the analog of
    the reference's per-subproblem TBB tasks (DIVERGENCES #16).  Identity
    is scheduling-proof because each lane's chain swaps into ITS WORKER's
    thread-local ``RandomState`` (``lane_rng`` operates per-thread), so
    every lane performs exactly the draws its sequential run performs no
    matter how the pool interleaves — or which thread runs a lane when
    the map degrades to the caller.  ``pool`` reuses the runner's shared
    executor (one per batch, not one per stage — the host IP/extension
    stages are the pipeline's serial tail).  ``disable_timers`` guards the
    global timer tree exactly as the reference disables timers inside its
    tbb task arena (and as deep._extend_partition_host does)."""
    from concurrent.futures import ThreadPoolExecutor

    from ..context import propagate_runtime

    # Workers re-activate the dispatcher thread's EngineRuntime: the
    # per-lane IP/extension stages resolve layout/sync settings, and
    # thread-local activation does not cross pool threads (PR 6 class).
    wfn = propagate_runtime(fn)

    def _run() -> list:
        if pool is not None:
            return list(pool.map(wfn, range(L)))
        workers = host_pool_workers(L)
        if workers <= 1:
            return [fn(i) for i in range(L)]
        with ThreadPoolExecutor(max_workers=workers) as tpool:
            return list(tpool.map(wfn, range(L)))

    if disable_timers:
        from ..utils.timer import Timer

        timer = Timer.global_()
        timer.disable()
        try:
            return _run()
        finally:
            timer.enable()
    return _run()


def _group_indices(keys) -> List[List[int]]:
    """Stable grouping: lanes with equal keys, first-occurrence order."""
    groups: Dict[object, List[int]] = {}
    order: List[object] = []
    for i, key in enumerate(keys):
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    return [groups[key] for key in order]


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class LaneStackReport:
    """What one lane-stacked batch execution did (engine stats surface)."""

    lanes: int = 0
    cohorts: int = 0
    splits: int = 0
    levels: int = 0
    stacked_pulls: int = 0
    # Per-lane cohort ordinal in request order (round 20): which cohort of
    # this batch execution each request's lane rode — the isolated-node
    # strip can move work graphs across stack buckets, so requests from
    # one shape cell may split across cohorts.  The engine's request-trace
    # lanestack event records it per request.
    lane_cohorts: tuple = ()
    # The stacked kernel shapes this run actually dispatched: level-0
    # stack buckets plus every coarsening level's (layout signature, lane
    # count).  Together with (k, epsilon) this names the executable set,
    # so the engine's warm accounting can key on what really compiled —
    # the request cell alone can't (the isolated-node strip moves work
    # graphs across buckets, and cohort splits change lane counts).
    layout_key: tuple = ()
    # Per-request final (k,) max block weights in request order — what the
    # sequential facade would leave in ctx.partition.max_block_weights
    # (the engine's feasibility check consumes them).
    caps: Optional[List[np.ndarray]] = None


class LaneStackRunner:
    """One batch execution.  ``run`` returns per-request partitions in
    request order, bit-identical to sequential facade runs."""

    def __init__(self, ctx: Context, graphs: Sequence, k: int, epsilon: float):
        self.base_ctx = ctx
        self.graphs = list(graphs)
        self.k = int(k)
        self.epsilon = float(epsilon)
        self.report = LaneStackReport(lanes=len(self.graphs))
        self._layout_shapes: set = set()
        self._pool = None  # shared host thread pool, owned by run()

    # -- facade replica (kaminpar.py per-request prep) ---------------------

    def _prep_lane(self, slot: int, graph) -> _Lane:
        ctx = self.base_ctx
        k = self.k
        chain = LaneChain(ctx.seed)  # the facade's per-call reseed
        # ONE counted pull materializes the request graph host-side
        # (kway.graph_to_host packs all four CSR arrays into a single
        # transfer); raw np.asarray reads would bypass the sync census on
        # the serve hot path.  scoped(): prep may run on a pool worker
        # thread whose phase stack is empty.
        from ..partitioning.kway import graph_to_host

        with sync_stats.scoped("serve_lanestack"):
            host = graph_to_host(graph)
        rp, ci, nw, ew = host.row_ptr, host.col_idx, host.node_w, host.edge_w
        # The facade's weighted-mode auto-pin, from the ORIGINAL graph.
        weighted = bool(ew.size and ew.min() != ew.max())
        # Per-lane ctx: own partition tree + the weighted-mode pin the
        # facade would set; shared read-only subtrees stay shared.
        lane_ctx = copy.copy(ctx)
        lane_ctx.partition = dataclasses.replace(ctx.partition)
        lane_ctx.coarsening = dataclasses.replace(
            ctx.coarsening,
            lp=dataclasses.replace(ctx.coarsening.lp, weighted_mode=weighted),
        )
        total_node_weight = int(graph.total_node_weight)
        max_node_weight = int(graph.max_node_weight)
        lane_ctx.partition.setup(total_node_weight, k, self.epsilon, 0.0)
        perfect = (total_node_weight + k - 1) // k
        lane_ctx.partition.max_block_weights = np.maximum(
            lane_ctx.partition.max_block_weights, perfect + max_node_weight
        )
        caps = np.asarray(lane_ctx.partition.max_block_weights, dtype=np.int64)

        # Isolated-node strip (the facade's exact helper, graph/isolated.py)
        # on the already-materialized host arrays.
        stripped = strip_isolated_csr(rp, ci, nw, graph.n, k)
        ew32 = ew.astype(np.int32, copy=False)
        if stripped is not None:
            keep, isolated, new_rp, new_col, new_nw = stripped
            work = {
                "row_ptr": new_rp.astype(np.int32),
                "col_idx": new_col.astype(np.int32),
                "node_w": new_nw.astype(np.int32),
                "edge_w": ew32,
            }
        else:
            keep = isolated = None
            work = {
                "row_ptr": rp.astype(np.int32, copy=False),
                "col_idx": ci.astype(np.int32, copy=False),
                "node_w": nw.astype(np.int32, copy=False),
                "edge_w": ew32,
            }
        work_n = len(work["row_ptr"]) - 1
        work_m = len(work["col_idx"])
        return _Lane(
            slot=slot, graph=graph, chain=chain, ctx=lane_ctx, caps=caps,
            keep=keep, isolated=isolated, work_host=work,
            work_n=work_n, work_m=work_m,
            tnw=int(work["node_w"].astype(np.int64).sum()),
            weighted=weighted,
        )

    # -- stacked level construction ----------------------------------------

    def _stack_level0(self, lanes: List[_Lane]) -> _Level:
        n_pad = _next_bucket(max(l.work_n for l in lanes))
        m_pad = _next_bucket(max(l.work_m for l in lanes))
        self._layout_shapes.add(("l0", n_pad, m_pad, len(lanes)))
        # All lanes share the cell by grouping, so per-lane buckets equal
        # the shared ones (asserted by the caller's grouping key).
        L = len(lanes)
        rp = np.zeros((L, n_pad + 1), dtype=np.int32)
        col = np.zeros((L, m_pad), dtype=np.int32)
        nw = np.zeros((L, n_pad), dtype=np.int32)
        ew = np.zeros((L, m_pad), dtype=np.int32)
        eu = np.zeros((L, m_pad), dtype=np.int32)
        hist = []
        max_nw = np.zeros(L, dtype=np.int64)
        anchor = n_pad - 1
        for i, lane in enumerate(lanes):
            w = lane.work_host
            n, m = lane.work_n, lane.work_m
            rp[i, : n + 1] = w["row_ptr"]
            rp[i, n + 1 : n_pad] = m
            rp[i, n_pad] = m_pad
            col[i, :m] = w["col_idx"]
            col[i, m:] = anchor
            nw[i, :n] = w["node_w"]
            ew[i, :m] = w["edge_w"]
            deg = np.diff(w["row_ptr"])
            eu[i, :m] = np.repeat(np.arange(n, dtype=np.int32), deg)
            eu[i, m:] = anchor
            hist.append(host_deg_histogram(w["row_ptr"], n))
            max_nw[i] = int(w["node_w"].max()) if n else 0
        return _Level(
            row_ptr=jnp.asarray(rp), col_idx=jnp.asarray(col),
            node_w=jnp.asarray(nw), edge_w=jnp.asarray(ew),
            edge_u=jnp.asarray(eu),
            n=np.asarray([l.work_n for l in lanes], dtype=np.int64),
            m=np.asarray([l.work_m for l in lanes], dtype=np.int64),
            hist=hist, max_nw=max_nw,
        )

    def _layout(self, level: _Level):
        """Stacked bucketed views under the shared width signature (the
        caller guarantees signature equality across the level's lanes)."""
        if level.layout is None:
            plan, merged_to, counts, hs, Hr_pad, Hs_pad = lops.lane_layout_plan(
                level.hist
            )
            buckets, heavy, gather_idx = lops.lane_bucketed(
                level.row_ptr, level.col_idx, level.edge_w, level.edge_u,
                jnp.asarray(level.n), jnp.asarray(merged_to),
                jnp.asarray(counts), jnp.asarray(hs),
                plan=plan, Hr_pad=Hr_pad, Hs_pad=Hs_pad,
            )
            level.layout = (buckets, heavy, gather_idx)
        return level.layout

    # -- lockstep coarsening ----------------------------------------------

    def _coarsen(self, cohort: _Cohort) -> List[_Cohort]:
        """Coarsen lanes in lockstep; returns cohorts that finished (their
        ``levels[-1]`` is the coarsest graph).  Mirrors
        ClusterCoarsener.coarsen + coarsen_once per lane."""
        ctx = self.base_ctx
        target_n = 2 * ctx.coarsening.contraction_limit
        finished: List[_Cohort] = []
        queue = [cohort]
        while queue:
            c = queue.pop()
            cur = c.levels[-1]
            # Signature grouping comes FIRST — before the stop/go split —
            # because a cohort that stops here hands this level straight to
            # the stacked *refinement* path, whose draw shapes (bucketed
            # layout row pads, heavy pads) must equal every lane's own
            # sequential layout just like the clustering kernel's.
            sigs = [lops.lane_layout_signature(h) for h in cur.hist]
            groups = _group_indices(sigs)
            if len(groups) > 1:
                self.report.splits += len(groups) - 1
                queue.extend(c.select(g) for g in groups)
                continue
            stop = [i for i in range(c.L) if cur.n[i] <= target_n]
            go = [i for i in range(c.L) if cur.n[i] > target_n]
            if stop and go:
                self.report.splits += 1
                finished.append(c.select(stop))
                c = c.select(go)
                cur = c.levels[-1]
            elif stop:
                finished.append(c)
                continue
            queue.extend(self._coarsen_level(c, finished))
        return finished

    def _coarsen_level(self, c: _Cohort, finished: List[_Cohort]) -> List[_Cohort]:
        """One lockstep coarsening level over a signature-uniform cohort.
        Converged lanes are appended to ``finished``; continuing lanes come
        back (possibly split by coarse bucket)."""
        ctx = self.base_ctx
        cc = ctx.coarsening
        cur = c.levels[-1]
        L = c.L
        self.report.levels += 1
        # Cohort is signature-uniform here (grouped in _coarsen), so one
        # lane's signature names this level's stacked dispatch shapes.
        self._layout_shapes.add(
            ("lvl", lops.lane_layout_signature(cur.hist[0]), L)
        )
        buckets, heavy, gather_idx = self._layout(cur)

        # Per-lane host parameters (lp_clusterer._one_clustering replica).
        weighted = c.lanes[0].weighted  # uniform within a cohort
        active_prob = cc.lp.active_prob
        if weighted:
            # lp_clusterer's weighted-graph mode (per-lane static; cohorts
            # group by the flag).
            active_prob = min(active_prob, cc.lp.weighted_active_prob)
        max_cw = np.zeros(L, dtype=np.int64)
        iters = np.zeros(L, dtype=np.int64)
        min_moved = np.zeros(L, dtype=np.int64)
        for i, lane in enumerate(c.lanes):
            from ..coarsening.max_cluster_weights import compute_max_cluster_weight

            n_i, m_i = int(cur.n[i]), int(cur.m[i])
            mcw = compute_max_cluster_weight(
                cc, n_i, lane.tnw, self.k, self.epsilon
            )
            if cc.max_shrink_factor > 0:
                avg_w = lane.tnw / max(n_i, 1)
                mcw = min(mcw, max(int(cc.max_shrink_factor * avg_w), 1))
            max_cw[i] = mcw
            it = cc.lp.num_iterations
            if weighted:
                it *= max(cc.lp.weighted_sweep_factor, 1)
            elif n_i > 0 and m_i / n_i < cc.lp.low_degree_boost_threshold:
                it *= max(cc.lp.low_degree_boost_factor, 1)
            iters[i] = it
            min_moved[i] = int(cc.lp.min_moved_fraction * n_i)

        keys_iter = jnp.stack([lane.chain.next_key() for lane in c.lanes])
        if cc.lp.cluster_two_hop_nodes:
            keys_2h = jnp.stack([lane.chain.next_key() for lane in c.lanes])
        else:
            keys_2h = keys_iter  # unread
        labels, moved = lops.lane_cluster(
            cur.row_ptr, cur.node_w, buckets, heavy, gather_idx,
            keys_iter, keys_2h, jnp.asarray(cur.n), jnp.asarray(max_cw),
            jnp.asarray(min_moved), jnp.asarray(iters),
            num_labels=cur.n_pad, active_prob=active_prob,
            tie_break=cc.lp.tie_breaking.value,
            cluster_isolated=cc.lp.cluster_isolated_nodes,
            cluster_two_hop=cc.lp.cluster_two_hop_nodes,
        )
        coarse_of, stats, c_node_w, out_u, out_v, out_w, row_ptr = (
            lops.lane_contract(
                labels, cur.edge_u, cur.col_idx, cur.edge_w, cur.node_w, moved
            )
        )
        # THE one stacked blocking readback of the level (lane-accounted).
        stats_np = sync_stats.pull(
            stats, phase="lanestack_coarsening", lanes=L
        )
        self.report.stacked_pulls += 1

        from ..ops.contraction import STATS_LEN

        n_c = stats_np[:, 0].astype(np.int64) - 1  # drop the anchor cluster
        m_c = stats_np[:, 1].astype(np.int64)
        # Per-lane quality probes from values THIS stacked pull already
        # produced (cluster_coarsener's probe, lane-tagged; no-op without
        # an active trace recorder, never an extra transfer).
        for i in range(L):
            probes.coarsening_level(
                level=len(c.levels) - 1, n=int(cur.n[i]), m=int(cur.m[i]),
                n_c=int(n_c[i]), m_c=int(m_c[i]),
                max_cluster_weight=int(max_cw[i]),
                max_node_weight=int(stats_np[i, 2]),
                total_edge_weight=int(stats_np[i, 3]),
                lp_moved=int(stats_np[i, STATS_LEN]),
                lp_rounds_budget=cc.lp.num_iterations, lane=i,
            )
        conv, cont = [], []
        for i in range(L):
            shrink = 1.0 - n_c[i] / max(int(cur.n[i]), 1)
            (conv if shrink < cc.convergence_threshold else cont).append(i)
        if conv:
            # Whole-cohort convergence (the common same-family case) keeps
            # the cohort as-is — select() would copy every stacked level
            # for an identity subset.
            finished.append(c.select(conv) if cont else c)
            if cont:
                self.report.splits += 1
        if not cont:
            return []
        # Group continuing lanes by their coarse shape buckets (draw shapes
        # at the next level must equal each lane's own sequential buckets).
        out: List[_Cohort] = []
        bucket_groups = _group_indices(
            [(_next_bucket(int(n_c[i])), _next_bucket(int(m_c[i]))) for i in cont]
        )
        if len(bucket_groups) > 1:
            self.report.splits += len(bucket_groups) - 1
        take_all = lambda arr, idx: jnp.take(arr, jnp.asarray(idx), axis=0)
        for grp in bucket_groups:
            idx = [cont[j] for j in grp]
            n_pad = _next_bucket(int(n_c[idx[0]]))
            m_pad = _next_bucket(int(m_c[idx[0]]))
            rp_p, col_p, nw_p, ew_p, eu_p = lops.lane_extract_padded(
                take_all(row_ptr, idx), take_all(c_node_w, idx),
                take_all(out_u, idx), take_all(out_v, idx),
                take_all(out_w, idx),
                jnp.asarray(n_c[idx]), jnp.asarray(m_c[idx]),
                n_pad=n_pad, m_pad=m_pad,
            )
            sub = c.select(idx)
            sub.levels.append(_Level(
                row_ptr=rp_p, col_idx=col_p, node_w=nw_p, edge_w=ew_p,
                edge_u=eu_p, n=n_c[idx], m=m_c[idx],
                hist=[stats_np[i, 4:STATS_LEN].astype(int) for i in idx],
                max_nw=stats_np[idx, 2].astype(np.int64),
                coarse_of=take_all(coarse_of, idx),
            ))
            out.append(sub)
        return out

    # -- initial partitioning (per lane, host orchestration) ---------------

    def _initial_partition(self, c: _Cohort, cur_k: int):
        """Per-lane recursive bipartition on the coarsest graphs, fed from
        ONE stacked bulk pull (the graph_to_host twin).  The lanes run on
        the :func:`_map_lanes` thread pool — host IP is the serial tail of
        the lockstep pipeline, and the lanes are independent subproblems."""
        cur = c.levels[-1]
        packed = sync_stats.pull(
            jnp.concatenate(
                [cur.row_ptr, cur.col_idx, cur.node_w, cur.edge_w], axis=1
            ),
            phase="lanestack_ip", lanes=c.L,
        )
        self.report.stacked_pulls += 1
        n_pad, m_pad = cur.n_pad, cur.m_pad

        def one(i: int):
            lane = c.lanes[i]
            n_i, m_i = int(cur.n[i]), int(cur.m[i])
            row = packed[i]
            host = HostCSR(
                row[: n_i + 1].astype(np.int64),
                row[n_pad + 1 : n_pad + 1 + m_i].astype(np.int64),
                row[n_pad + 1 + m_pad : n_pad + 1 + m_pad + n_i].astype(np.int64),
                row[n_pad + 1 + m_pad + n_pad :][:m_i].astype(np.int64),
            )
            budgets = intermediate_block_weights(lane.caps, cur_k)
            with lane_rng(lane.chain):
                rng = RandomState.numpy_rng()  # deep.py's pre-IP draw
                return recursive_bipartition(
                    host, cur_k, budgets, rng,
                    lane.ctx.initial_partitioning,
                )

        parts = _map_lanes(one, c.L, pool=self._pool, disable_timers=True)
        return self._stack_labels(parts, n_pad)

    @staticmethod
    def _stack_labels(parts: List[np.ndarray], n_pad: int):
        L = len(parts)
        out = np.zeros((L, n_pad), dtype=np.int32)
        for i, p in enumerate(parts):
            out[i, : len(p)] = p
        return jnp.asarray(out)

    # -- lockstep refinement ------------------------------------------------

    def _block_caps(self, c: _Cohort, level: _Level, cur_k: int,
                    coarse: bool) -> np.ndarray:
        """(L, cur_k) per-lane intermediate budgets (deep._refine replica)."""
        eps = self.epsilon
        out = np.zeros((c.L, cur_k), dtype=np.int64)
        for i, lane in enumerate(c.lanes):
            mb = intermediate_block_weights(lane.caps, cur_k)
            if coarse:
                relaxed = np.ceil(mb / (1.0 + eps)).astype(np.int64) + int(
                    level.max_nw[i]
                )
                mb = np.maximum(mb, relaxed)
            out[i] = mb
        return out

    def _quality(self, level: _Level, labels, cur_k: int) -> np.ndarray:
        """(L, 1 + cur_k) [cut, block_weights...] via one stacked pull."""
        q = lops.lane_quality(
            labels, level.node_w, level.edge_u, level.col_idx, level.edge_w,
            k=cur_k,
        )
        out = sync_stats.pull(
            q, phase="lanestack_refinement", lanes=level.row_ptr.shape[0]
        )
        self.report.stacked_pulls += 1
        return out.astype(np.int64)

    def _refine(self, c: _Cohort, level: _Level, labels, cur_k: int,
                coarse: bool):
        """MultiRefiner keep-best over the stacked (balancer, LP) chain —
        refiner.py's rank/chain semantics per lane."""
        ctx = self.base_ctx
        caps = self._block_caps(c, level, cur_k, coarse)
        caps_dev = jnp.asarray(caps.astype(np.int32))
        buckets, heavy, gather_idx = self._layout(level)

        def ranks(q):
            # (infeasible, cut) per lane; min-feasibility is trivially true
            # in the envelope (no minimum block weights).
            return [
                (bool(np.any(q[i, 1:] > caps[i])), int(q[i, 0]))
                for i in range(c.L)
            ]

        snapshots = [labels]
        best_idx = [0] * c.L
        best_rank = ranks(self._quality(level, labels, cur_k))

        # --- overload balancer (balancer.py round-loop replica) -----------
        active = [True] * c.L
        lab = labels
        dummy = rng.seed_key(0)
        for _ in range(ctx.refinement.balancer.max_num_rounds):
            keys = jnp.stack([
                lane.chain.next_key() if active[i] else dummy
                for i, lane in enumerate(c.lanes)
            ])
            lab, flags = lops.lane_balance_round(
                keys, lab, buckets, heavy, gather_idx, level.node_w,
                caps_dev, jnp.asarray(active), k=cur_k,
            )
            flags_np = sync_stats.pull(
                flags, phase="lanestack_refinement", lanes=c.L
            )
            self.report.stacked_pulls += 1
            for i in range(c.L):
                if active[i] and (
                    not flags_np[i, 1] or flags_np[i, 0] == 0
                ):
                    active[i] = False
            if not any(active):
                break
        snapshots.append(lab)
        rank_b = ranks(self._quality(level, lab, cur_k))
        for i in range(c.L):
            if rank_b[i] <= best_rank[i]:
                best_rank[i], best_idx[i] = rank_b[i], 1

        # --- LP refiner (lp_refiner.py replica) ----------------------------
        rl = ctx.refinement.lp
        k_pad = num_labels_bucket(cur_k)
        max_w = np.zeros((c.L, k_pad), dtype=np.int32)
        max_w[:, :cur_k] = caps.astype(np.int32)
        keys = jnp.stack([lane.chain.next_key() for lane in c.lanes])
        min_moved = np.asarray(
            [int(rl.min_moved_fraction * int(level.n[i])) for i in range(c.L)],
            dtype=np.int64,
        )
        iters = np.full(c.L, rl.num_iterations, dtype=np.int64)
        lab_lp = lops.lane_lp_refine(
            lab, keys, buckets, heavy, gather_idx, level.node_w,
            jnp.asarray(max_w), jnp.asarray(min_moved), jnp.asarray(iters),
            jnp.asarray(level.n),
            num_labels=k_pad, active_prob=rl.active_prob,
            allow_tie_moves=rl.allow_tie_moves,
        )
        snapshots.append(lab_lp)
        rank_lp = ranks(self._quality(level, lab_lp, cur_k))
        for i in range(c.L):
            if rank_lp[i] <= best_rank[i]:
                best_rank[i], best_idx[i] = rank_lp[i], 2
        # (A trailing underload balancer is a no-op without minimum block
        # weights and cannot change the keep-best outcome.)

        return lops.lane_select_best(
            jnp.stack(snapshots), jnp.asarray(best_idx, dtype=np.int32)
        )

    # -- extension (per lane, host orchestration) ---------------------------

    def _lane_graph_view(self, level: _Level, i: int, lane: _Lane) -> CSRGraph:
        """Lane ``i``'s graph at ``level`` as a real CSRGraph (device slices
        + pre-seeded padded view) for the unmodified host extension path."""
        n_i, m_i = int(level.n[i]), int(level.m[i])
        rp = level.row_ptr[i]
        col = level.col_idx[i]
        nw = level.node_w[i]
        ew = level.edge_w[i]
        eu = level.edge_u[i]
        g = CSRGraph(
            rp[: n_i + 1], col[:m_i], nw[:n_i], ew[:m_i], edge_u=eu[:m_i]
        )
        g._padded = PaddedView(rp, col, nw, ew, eu, n_i, m_i)
        g._deg_hist = level.hist[i]  # host (12,) histogram (see _Level)
        g._layout_mode = lane.ctx.parallel.device_layout_build
        g._total_node_weight = lane.tnw
        g._max_node_weight = int(level.max_nw[i])
        return g

    def _extend(self, c: _Cohort, level: _Level, labels, cur_k: int,
                target_k: int):
        """Per-lane host extension through the real ``extend_partition``
        (identical draws via the lane chain), fed from ONE stacked pull.
        Lanes run on the :func:`_map_lanes` pool — extension derives every
        block's stream from a reseed that already lands in ITS OWN inner
        worker (deep._extend_partition_host), so outer-lane scheduling
        cannot reorder any draw."""
        from ..partitioning.deep import extend_partition

        lab_np = sync_stats.pull(
            labels, phase="lanestack_extend", lanes=c.L
        )
        self.report.stacked_pulls += 1

        def one(i: int):
            lane = c.lanes[i]
            g = self._lane_graph_view(level, i, lane)
            with lane_rng(lane.chain):
                return extend_partition(
                    g, lab_np[i, : int(level.n[i])].astype(np.int32),
                    cur_k, target_k, lane.ctx,
                )

        parts = _map_lanes(one, c.L, pool=self._pool)
        return self._stack_labels(parts, level.n_pad)

    # -- the deep uncoarsening loop (deep.py partition() replica) -----------

    def _uncoarsen_phase(self, c: _Cohort) -> List[Tuple[_Lane, np.ndarray]]:
        """IP + extend/refine/uncoarsen lockstep for one finished cohort;
        returns (lane, work-graph partition) pairs."""
        ctx = self.base_ctx
        C = ctx.coarsening.contraction_limit
        out: List[Tuple[_Lane, np.ndarray]] = []

        # cur_k may differ across lanes (it depends on the coarsest n).
        cur_ks = [
            min(self.k, compute_k_for_n(int(c.levels[-1].n[i]), C, self.k))
            for i in range(c.L)
        ]
        groups = _group_indices(cur_ks)
        if len(groups) > 1:
            self.report.splits += len(groups) - 1
        for grp in groups:
            sub = c.select(grp) if len(groups) > 1 else c
            cur_k = cur_ks[grp[0]]
            labels = self._initial_partition(sub, cur_k)
            depth = len(sub.levels) - 1
            labels = self._refine(
                sub, sub.levels[-1], labels, cur_k, coarse=depth > 0
            )
            out.extend(self._finish_from(sub, labels, cur_k, depth))
        return out

    def _finish_from(self, sub: _Cohort, labels, cur_k: int,
                     level_idx: int) -> List[Tuple[_Lane, np.ndarray]]:
        """Continue the uncoarsening loop for a split-off sub-cohort from
        ``level_idx`` with the given stacked labels."""
        ctx = self.base_ctx
        C = ctx.coarsening.contraction_limit
        out: List[Tuple[_Lane, np.ndarray]] = []
        while True:
            cur = sub.levels[level_idx]
            tks = [
                (compute_k_for_n(int(cur.n[i]), C, self.k)
                 if level_idx > 0 else self.k)
                for i in range(sub.L)
            ]
            tk_groups = _group_indices(tks)
            if len(tk_groups) > 1:
                self.report.splits += len(tk_groups) - 1
                for tg in tk_groups:
                    out.extend(self._finish_from(
                        sub.select(tg),
                        jnp.take(labels, jnp.asarray(tg), axis=0),
                        cur_k, level_idx,
                    ))
                return out
            target_k = min(self.k, tks[0]) if level_idx > 0 else self.k
            if cur_k < target_k:
                labels = self._extend(sub, cur, labels, cur_k, target_k)
                cur_k = target_k
                labels = self._refine(
                    sub, cur, labels, cur_k, coarse=level_idx > 0
                )
            if level_idx == 0:
                lab_np = sync_stats.pull(
                    labels, phase="lanestack_refinement", lanes=sub.L
                )
                self.report.stacked_pulls += 1
                for i, lane in enumerate(sub.lanes):
                    out.append((
                        lane, lab_np[i, : int(cur.n[i])].astype(np.int32)
                    ))
                return out
            labels = lops.lane_project(cur.coarse_of, labels)
            level_idx -= 1
            labels = self._refine(
                sub, sub.levels[level_idx], labels, cur_k,
                coarse=level_idx > 0,
            )

    # -- finalize (facade replica: isolated re-integration) -----------------

    def _finalize(self, lane: _Lane, work_part: np.ndarray) -> np.ndarray:
        if lane.keep is None:
            part = work_part
        else:
            # The facade's exact re-integration helper (graph/isolated.py).
            part = assign_isolated_nodes(
                lane.graph.n, self.k, lane.keep, lane.isolated, work_part,
                lane.work_host["node_w"],
                sync_stats.pull(lane.graph.node_w, phase="serve_lanestack"),
                lane.caps,
            )
        from ..utils.assertions import LIGHT, kassert

        kassert(
            lambda: part.size == 0
            or (part.min() >= 0 and part.max() < self.k),
            "partition labels out of range", LIGHT,
        )
        return part

    # -- entry ---------------------------------------------------------------

    def run(self) -> List[np.ndarray]:
        from concurrent.futures import ThreadPoolExecutor

        check_eligibility(self.base_ctx, self.graphs, self.k)
        workers = host_pool_workers(len(self.graphs))
        if workers > 1:
            # ONE host pool for every per-lane IP/extension stage of the
            # batch (thread churn would sit on the host serial tail).
            with ThreadPoolExecutor(max_workers=workers) as pool:
                self._pool = pool
                try:
                    return self._run()
                finally:
                    self._pool = None
        return self._run()

    def _run(self) -> List[np.ndarray]:
        with scoped_timer("serve_lanestack"):
            # Per-lane prep (host materialization + isolated strip) is
            # independent O(n+m) work — map it over the batch pool like
            # the IP/extension stages.
            lanes = _map_lanes(
                lambda i: self._prep_lane(i, self.graphs[i]),
                len(self.graphs), pool=self._pool,
            )
            self.report.caps = [lane.caps for lane in lanes]
            # Work graphs can leave the request cell (isolated-node strip
            # shrinks n); group by the stacked level-0 buckets.
            results: List[Optional[np.ndarray]] = [None] * len(lanes)
            cohorts = _group_indices([
                (_next_bucket(l.work_n), _next_bucket(l.work_m), l.weighted)
                for l in lanes
            ])
            self.report.cohorts = len(cohorts)
            lane_cohorts = [0] * len(lanes)
            for ci, grp in enumerate(cohorts):
                for li in grp:
                    lane_cohorts[li] = ci
            self.report.lane_cohorts = tuple(lane_cohorts)
            pre = sync_stats.phase_count("lanestack_coarsening")
            for grp in cohorts:
                c = _Cohort(lanes=[lanes[i] for i in grp])
                c.levels.append(self._stack_level0(c.lanes))
                finished = self._coarsen(c)
                for fc in finished:
                    for lane, work_part in self._uncoarsen_phase(fc):
                        results[lane.slot] = self._finalize(lane, work_part)
            self.report.layout_key = tuple(sorted(self._layout_shapes))
            attempts = self.report.levels
            # In-pipeline lane-accounted budget assert: exactly ONE stacked
            # blocking readback per attempted coarsening level per cohort
            # path (armed via sync_stats.enable_budget_checks, like the
            # sequential spine's per-level budget in deep.py).
            sync_stats.assert_phase_budget(
                "lanestack_coarsening", attempts, since=pre
            )
            from ..utils.assertions import LIGHT, kassert

            kassert(
                lambda: all(r is not None for r in results),
                "lane-stacked run dropped a lane (cohort-split invariant)",
                LIGHT,
            )
            return results


def run_lanestacked(ctx: Context, graphs: Sequence, k: int, epsilon: float,
                    trace_lane: str = ""):
    """Execute a same-cell batch lane-stacked; returns (partitions, report).
    Raises :class:`LaneStackUnsupported` for out-of-envelope batches.

    ``trace_lane`` (round 18, serve/fleet.py): when set and a trace
    recorder is active, the whole stacked execution additionally lands as
    ONE closed span on the named synthetic lane row (``replicaN``), so a
    fleet trace shows the device axis side by side — which replica ran
    which stacked batch at what occupancy — without touching the ambient
    thread rows."""
    from ..resilience.faults import maybe_inject

    # Named "execute" injection point of the stacked path (round 17): the
    # engine's lanestack breaker + per-graph fallback are exercised by
    # chaos plans targeting site "lanestack".  Before any lane prep, so a
    # faulted batch leaves no partial per-lane state behind.
    maybe_inject("execute", site="lanestack")
    runner = LaneStackRunner(ctx, graphs, k, epsilon)
    from ..telemetry import trace as ttrace

    rec = ttrace.active() if trace_lane else None
    t0 = rec._now_us() if rec is not None else 0.0
    parts = runner.run()
    if rec is not None:
        rec.lane_span(
            trace_lane, "lanestack_batch", t0, rec._now_us(),
            lanes=runner.report.lanes, cohorts=runner.report.cohorts,
            splits=runner.report.splits,
        )
    return parts, runner.report
