"""Crash-safe serve journal (ISSUE 15 tentpole b).

An append-only JSONL record of the engine's accepted work: every
admitted request writes an **admit** record (request params + the graph
payload, serialized host-side through the same ONE-counted-pull
``graph_to_host`` discipline as the pipeline, under the
``journal_write`` phase) and every first-wins future finalization
writes a **resolution** record.  fsync is batched (``fsync_every``
appends) — the un-fsynced suffix is the crash-loss window; resolutions
and the warm-state record force an fsync so a recorded outcome is
durable before its caller can act on it.

On restart, :meth:`PartitionEngine.start` replays the journal:

* admits with **no** resolution record are re-enqueued idempotently
  (``journal_replay`` phase; the replay bypasses the admission bound —
  the work was admitted once already) and resolve into fresh resolution
  records, so restart mid-burst loses ZERO accepted requests and the
  final journal carries exactly one resolution per admit
  (duplicates are impossible: only unresolved entries replay, and the
  engine's first-wins future finalization already dedupes in-process);
* the latest **warm_state** record restores the warmup report, warm
  cells, lane-stack layout keys, service-time EMA seed, and open
  breaker trips through the PR 14 inheritance path — the restarted
  replica starts warm with a ZERO warmup compile-event delta (the
  shared persistent XLA cache dir covers the cross-process executables).

A torn trailing line (a kill mid-append) is tolerated and counted, not
fatal.  Rejections that mean "the engine gave the request back"
(EngineStoppedError / WorkerHung — PR 14's resteerable classes) are NOT
journaled as resolutions: they leave the entry replayable, which is the
whole point of the journal.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


def _b64(arr: np.ndarray) -> dict:
    import base64

    arr = np.ascontiguousarray(arr)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def _unb64(payload: dict) -> np.ndarray:
    import base64

    return np.frombuffer(
        base64.b64decode(payload["b64"]), dtype=np.dtype(payload["dtype"])
    ).reshape(payload["shape"]).copy()


def encode_graph(graph) -> dict:
    """Host-serialize a CSR graph for an admit record — ONE counted bulk
    pull (``graph_to_host``); the caller scopes it under the
    ``journal_write`` phase."""
    from ..partitioning.kway import graph_to_host

    host = graph_to_host(graph)
    return {
        "n": int(graph.n),
        "m": int(graph.m),
        "row_ptr": _b64(host.row_ptr),
        "col_idx": _b64(host.col_idx),
        "node_w": _b64(host.node_w),
        "edge_w": _b64(host.edge_w),
    }


def decode_graph(payload: dict, use_64bit: bool = False,
                 layout_mode: Optional[str] = None):
    """Rebuild the CSR graph of an admit record (host->device puts only;
    same n/m -> same shape-ladder buckets as the original admission)."""
    from ..graph.csr import from_numpy_csr

    g = from_numpy_csr(
        _unb64(payload["row_ptr"]), _unb64(payload["col_idx"]),
        _unb64(payload["node_w"]), _unb64(payload["edge_w"]),
        use_64bit=use_64bit,
    )
    g._layout_mode = layout_mode
    return g


def _to_tuple(obj):
    """JSON round-trips tuples into lists; warm-state keys are tuples."""
    if isinstance(obj, list):
        return tuple(_to_tuple(x) for x in obj)
    return obj


class ServeJournal:
    """One engine's append-only journal file (thread-safe appends,
    batched fsync)."""

    def __init__(self, path: str, fsync_every: int = 8):
        self.path = path
        self.fsync_every = max(1, int(fsync_every))
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")  # noqa: SIM115 — held
        self._lock = threading.Lock()
        self._since_fsync = 0
        self.appended = 0
        self.fsyncs = 0
        self._closed = False

    def append(self, record: dict, force_fsync: bool = False) -> None:
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            if self._closed:
                return
            self._f.write(line + "\n")
            self._f.flush()
            self.appended += 1
            self._since_fsync += 1
            if force_fsync or self._since_fsync >= self.fsync_every:
                os.fsync(self._f.fileno())
                self._since_fsync = 0
                self.fsyncs += 1

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                self.fsyncs += 1
            finally:
                self._f.close()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "appended": self.appended,
                "fsyncs": self.fsyncs,
                "fsync_every": self.fsync_every,
            }


def read_journal(path: str) -> dict:
    """Parse a journal file into its recovery view:

    ``unresolved`` — admit records (in admit order) with no matching
    resolution; ``resolved`` — journal ids with a resolution record (and
    how many — replay conservation asserts exactly one each);
    ``warm_state`` — the LATEST warm-state record; ``torn`` — trailing
    lines that did not parse (a kill mid-append)."""
    admits: Dict[int, dict] = {}
    resolved: Dict[int, int] = {}
    warm_state: Optional[dict] = None
    order: List[int] = []
    torn = 0
    if not os.path.exists(path):
        return {"unresolved": [], "resolved": {}, "warm_state": None,
                "torn": 0, "admits": 0, "max_id": 0}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                torn += 1
                continue
            kind = rec.get("t")
            if kind == "admit":
                jid = int(rec["id"])
                admits[jid] = rec
                order.append(jid)
            elif kind == "resolve":
                jid = int(rec["id"])
                resolved[jid] = resolved.get(jid, 0) + 1
            elif kind == "warm_state":
                warm_state = rec  # latest wins
    unresolved = [admits[j] for j in order if j not in resolved]
    return {
        "unresolved": unresolved,
        "resolved": resolved,
        "warm_state": warm_state,
        "torn": torn,
        "admits": len(admits),
        # Journal ids are engine request ids; a restarted engine resumes
        # its counter PAST them so a new admission can never collide with
        # a dead run's journal entry.
        "max_id": max(list(admits) + list(resolved), default=0),
    }


def compact(path: str) -> int:
    """Rewrite the journal down to what a future recovery needs — the
    unresolved admits (in admit order) and the LATEST warm-state record —
    with the same atomic-rename discipline as the checkpoint writer.
    Called at clean engine shutdown: without it an append-only journal
    grows one graph payload per request forever and every restart
    re-parses the whole history.  Returns how many records were dropped.
    A crash mid-compaction leaves the original file intact."""
    view = read_journal(path)
    keep: List[dict] = list(view["unresolved"])
    if view["warm_state"] is not None:
        keep.append(view["warm_state"])
    try:
        with open(path, encoding="utf-8") as f:
            total = sum(1 for line in f if line.strip())
    except OSError:
        return 0
    dropped = total - len(keep)
    if dropped <= 0:
        return 0
    tmp = path + f".compact{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        for rec in keep:
            f.write(json.dumps(rec, separators=(",", ":"), default=str)
                    + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return dropped


# ---------------------------------------------------------------------------
# Warm-state round trip (the PR 14 inheritance path, serialized)
# ---------------------------------------------------------------------------


def warm_state_record(engine) -> dict:
    """Serialize the engine's warm state: warmup-report rows, warm cells
    / (n, k, tier) pairs / lane-stack layout keys, the service-time EMA,
    and which breaker cells are currently tripped open."""
    open_breakers = []
    snap = engine.breakers.snapshot()
    for name, br in snap["breakers"].items():
        if br["state"] != "closed":
            path, _, cell = name.partition("|")
            open_breakers.append(
                [path, [_int_or_str(c) for c in cell.split(",") if c != ""]]
            )
    return {
        "t": "warm_state",
        "warmup_report": list(engine.warmup_report),
        "warm_cells": [list(c) for c in engine._warm_cells],
        "warm_nk": [list(c) for c in engine._warm_nk],
        "warm_stack_keys": [list(c) for c in engine._warm_stack_keys],
        "ema_service_s": engine.stats_.service_time_estimate(),
        "open_breakers": open_breakers,
    }


def _int_or_str(text: str):
    try:
        return int(text)
    except ValueError:
        return text


def apply_warm_state(engine, record: dict) -> int:
    """Restore a warm-state record into a not-yet-started engine — the
    journal twin of :meth:`PartitionEngine.inherit_warmup`.  Rows land
    marked ``inherited`` (the cost was paid by the dead process), warm
    sets are seeded so ``start(warmup=True)`` skips every restored cell
    (zero compile events raised by warmup — asserted in
    tests/test_journal.py), the EMA seeds the retry-after estimate, and
    open breaker cells are re-tripped fresh (the cooldown restarts: the
    dead process's clock is meaningless here)."""
    from .batching import ShapeCell

    restored = 0
    for row in record.get("warmup_report", []):
        row = dict(row)
        row["inherited"] = True
        row["wall_s"] = 0.0
        row["backend_compile_s"] = 0.0
        row["trace_s"] = 0.0
        engine.warmup_report.append(row)
        restored += 1
    for cell in record.get("warm_cells", []):
        engine._warm_cells.add(ShapeCell(*[int(x) for x in cell]))
    for nk in record.get("warm_nk", []):
        engine._warm_nk.add((int(nk[0]), int(nk[1]), str(nk[2])))
    for key in record.get("warm_stack_keys", []):
        engine._warm_stack_keys.add(_to_tuple(key))
    ema = float(record.get("ema_service_s", 0.0) or 0.0)
    if ema > 0.0:
        engine.stats_.seed_service_time(ema)
    for path, cell in record.get("open_breakers", []):
        engine.breakers.get(str(path), tuple(cell)).trip()
    if restored or record.get("warm_cells"):
        engine._inherited = True
    return restored
