"""``python -m kaminpar_tpu.serve`` — the serving CLI.

Three modes:

* ``--warmup-only``: start the engine (ladder precompile), print the
  per-cell warmup report + stats snapshot as JSON, exit.  The same report
  is available offline via ``python -m kaminpar_tpu.tools warmup``.
* graph files as positionals: serve each file through the warm engine
  (one request per file), optionally writing ``<graph>.part`` outputs.
* ``--demo N`` (default when no graphs are given): run a synthetic
  burst workload of N RMAT requests across the warm ladder and print the
  stats snapshot — the quickest way to see batching/queueing behave.

Observability (ISSUE 5): ``--metrics-port P`` serves the engine's Prometheus
text exposition at ``http://127.0.0.1:P/metrics`` for the session's
duration — plus a JSON liveness probe at ``/healthz`` (round 20: queue and
dispatcher liveness per replica with the SLO burn summary; 200 healthy,
503 not); ``--trace-out FILE`` records the whole session (engine queue
lifecycle events + pipeline spans + quality probes) as a Chrome trace.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _int_tuple(text: str) -> tuple:
    return tuple(int(s) for s in text.split(",") if s.strip())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m kaminpar_tpu.serve",
        description="Partition-serving runtime: warm engine, bucket-batched "
        "dispatch, bounded async queue.",
    )
    p.add_argument("graphs", nargs="*", help="graph files to serve (METIS/ParHIP)")
    p.add_argument("-P", "--preset", default="serve")
    p.add_argument("-k", type=int, default=8, help="blocks per request")
    p.add_argument("-e", "--epsilon", type=float, default=0.03)
    p.add_argument("--ladder", type=_int_tuple, default=None,
                   help="warmup node-count rungs, e.g. 256,1024")
    p.add_argument("--warm-ks", type=_int_tuple, default=None,
                   help="warmup k values, e.g. 4,8")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--queue-bound", type=int, default=None)
    p.add_argument("--batch-window-ms", type=float, default=None)
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline (0 = none)")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="serve through a PartitionFleet of N per-device "
                        "engine replicas (round 18; 0 = single engine, "
                        "-1 = one replica per visible device)")
    p.add_argument("--warmup-only", action="store_true")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--demo", type=int, default=16, metavar="N",
                   help="synthetic burst requests when no graphs are given")
    p.add_argument("--demo-edge-factor", type=int, default=8)
    p.add_argument("-o", "--output", action="store_true",
                   help="write <graph>.part next to each served graph file")
    p.add_argument("--metrics-port", type=int, default=0, metavar="PORT",
                   help="serve Prometheus metrics at "
                        "http://127.0.0.1:PORT/metrics (0 = off)")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write a Chrome trace-event JSON of the session")
    return p


def _health_snapshot(engine) -> dict:
    """Liveness probe body (round 20): per-replica queue/dispatcher
    liveness plus the SLO burn summary.  Deliberately cheap — no
    ``stats()`` call, no device work — so a load balancer can poll it at
    high frequency without perturbing the serve path it is probing."""
    replicas = getattr(engine, "replicas", None) or [engine]
    rows = []
    for eng in replicas:
        queue = getattr(eng, "_queue", None)
        thread = getattr(eng, "_thread", None)
        tracker = getattr(eng, "_slo", None)
        rows.append({
            "engine": getattr(eng, "name", "") or "engine",
            "queue_open": bool(queue is not None and not queue.closed),
            "dispatcher_alive": bool(thread is not None and thread.is_alive()),
            "slo": (tracker.summary() if tracker is not None
                    else {"armed": False}),
        })
    healthy = bool(rows) and all(
        row["queue_open"] and row["dispatcher_alive"] for row in rows
    )
    return {"healthy": healthy, "replicas": rows}


def _start_metrics_server(engine, port: int):
    """Serve ``engine.metrics_text()`` at /metrics and a JSON liveness
    probe at /healthz (200 healthy / 503 not) on a daemon thread;
    returns the server (caller shuts it down)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.split("?")[0].rstrip("/")
            if path in ("", "/metrics"):
                body = engine.metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                health = _health_snapshot(engine)
                body = json.dumps(health).encode()
                self.send_response(200 if health["healthy"] else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # silence per-scrape stderr noise
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(
        target=server.serve_forever, name="kaminpar-serve-metrics", daemon=True
    ).start()
    return server


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..utils.platform import prefer_working_backend

    prefer_working_backend()
    from ..presets import create_context_by_preset_name
    from .engine import PartitionEngine

    ctx = create_context_by_preset_name(args.preset)
    overrides = {}
    if args.ladder is not None:
        overrides["warm_ladder"] = args.ladder
    if args.warm_ks is not None:
        overrides["warm_ks"] = args.warm_ks
    for flag, knob in (("max_batch", "max_batch"),
                       ("queue_bound", "queue_bound"),
                       ("batch_window_ms", "batch_window_ms"),
                       ("deadline_ms", "default_deadline_ms")):
        val = getattr(args, flag)
        if val is not None:
            overrides[knob] = val
    if args.fleet:
        # Fleet mode (round 18): N per-device replicas behind the
        # shape-cell router; the metrics endpoint serves the FLEET
        # exposition (per-replica expositions stay available in-process).
        from .fleet import PartitionFleet

        engine = PartitionFleet(
            ctx, replicas=(None if args.fleet < 0 else args.fleet),
            **overrides,
        )
    else:
        engine = PartitionEngine(ctx, **overrides)
    from ..telemetry import trace as ttrace

    rec = None
    if args.trace_out:
        rec = ttrace.start()
        rec.meta.update({"mode": "serve", "preset": args.preset,
                         "fleet": int(args.fleet)})
    metrics_server = None
    try:
        # Inside the try: a failed warmup or an already-bound metrics port
        # must still drain/shut the engine and write the requested trace.
        engine.start(warmup=not args.no_warmup)
        if args.metrics_port:
            metrics_server = _start_metrics_server(engine, args.metrics_port)
            print(f"metrics: http://127.0.0.1:{args.metrics_port}/metrics",
                  file=sys.stderr)
        if args.warmup_only:
            if args.fleet:
                print(json.dumps({
                    "warmup": [r.warmup_report for r in engine.replicas],
                    "stats": engine.stats(),
                }, default=str))
            else:
                print(json.dumps({"warmup": engine.warmup_report,
                                  "stats": engine.stats()}, default=str))
            return 0
        if args.graphs:
            from .. import io as kio

            futures = []
            for path in args.graphs:
                g = kio.read_graph(path)
                futures.append((path, engine.submit(g, args.k, args.epsilon)))
            for path, fut in futures:
                res = fut.result()
                print(f"RESULT graph={path} k={args.k} cut={res.cut} "
                      f"feasible={int(res.feasible)} "
                      f"batch={res.batch_size} warm={int(res.warm_hit)} "
                      f"wait_ms={res.queue_wait_s * 1e3:.1f} "
                      f"exec_ms={res.execute_s * 1e3:.1f}")
                if args.output:
                    kio.write_partition(path + ".part", res.partition)
        else:
            from ..graph.generators import rmat_graph

            ladder = engine.serve.warm_ladder or (256,)
            t0 = time.perf_counter()
            futures = []
            for i in range(args.demo):
                n = ladder[i % len(ladder)]
                scale = max(2, (int(n) - 1).bit_length())
                g = rmat_graph(scale, edge_factor=args.demo_edge_factor,
                               seed=100 + i)
                futures.append(engine.submit(g, args.k, args.epsilon))
            for fut in futures:
                fut.result()
            wall = time.perf_counter() - t0
            print(f"demo: {args.demo} requests in {wall:.2f}s "
                  f"({args.demo / wall:.2f} graphs/s)")
        print(json.dumps(engine.stats(), default=str))
        return 0
    finally:
        try:
            engine.shutdown(drain=True)
        finally:
            # A failed/interrupted drain must still stop the metrics server
            # and write the requested trace.
            if metrics_server is not None:
                metrics_server.shutdown()
            if rec is not None:
                ttrace.stop()
                try:
                    rec.write(args.trace_out)
                    print(f"trace written to {args.trace_out} "
                          f"({rec.summary()['events']} events)", file=sys.stderr)
                except OSError as exc:
                    # A failed trace write must neither mask the session's
                    # own exception nor crash a finished session at exit.
                    print(f"warning: could not write trace {args.trace_out}: "
                          f"{exc}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
