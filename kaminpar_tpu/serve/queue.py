"""Bounded request queue with same-cell batch extraction.

The admission side is strict (``put`` raises :class:`QueueFullError` when
the bound is hit — the engine wraps it with a retry-after estimate) and the
consumer side pops *micro-batches*: the oldest request seeds a batch and
later requests from the same shape cell join it, up to ``max_batch``,
optionally waiting a short batch window for stragglers.  Requests from
other cells keep their FIFO order — extracting a batch never reorders the
remainder.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from .batching import form_batches
from .errors import EngineStoppedError, QueueFullError


class BoundedServeQueue:
    """Thread-safe bounded FIFO of items carrying a ``.cell`` attribute."""

    def __init__(self, bound: int):
        if bound < 1:
            raise ValueError("queue bound must be >= 1")
        self.bound = int(bound)
        self._dq: deque = deque()
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return len(self._dq)

    def cell_depth(self, cell) -> int:
        """Queued requests in ``cell`` — the fleet router's batch-join
        signal (a replica with a *forming* same-cell batch, 0 < depth <
        max_batch, is preferred so the lane axis fills before load spills
        to the next device)."""
        with self._cv:
            return sum(1 for r in self._dq if r.cell == cell)

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def put(self, item, force: bool = False) -> None:
        """Admit one request; raises :class:`QueueFullError` at the bound
        and :class:`EngineStoppedError` after :meth:`close`.

        ``force`` (round 19) bypasses the bound — the journal replay
        re-enqueues work that was ADMITTED by the dead process, so the
        admission decision was already made once; bounding the replay
        would lose accepted requests, the one thing the journal exists
        to prevent (serve/journal.py)."""
        with self._cv:
            if self._closed:
                raise EngineStoppedError("queue closed; engine is draining")
            if not force and len(self._dq) >= self.bound:
                raise QueueFullError()
            self._dq.append(item)
            # Stamp the depth observed at admission (round 20): the
            # request-trace admit event records how deep in line this
            # request started, which the post-hoc dossier correlates with
            # its measured queue wait.
            if hasattr(item, "queue_position"):
                item.queue_position = len(self._dq)
            self._cv.notify_all()

    def pop_batch(self, max_batch: int, window_s: float = 0.0,
                  gate=None) -> Optional[List]:
        """Block until a request is available, then return a same-cell batch.

        The head request's cell seeds the batch; if fewer than ``max_batch``
        same-cell requests are queued, waits up to ``window_s`` for more to
        arrive before dispatching.  Returns ``None`` exactly once the queue
        is closed *and* drained (the graceful-shutdown termination signal).

        ``gate`` (round 18): an optional ``threading.Event`` — while it is
        cleared no batch is extracted, so ``PartitionEngine.pause`` holds
        work IN the queue (where a fleet drain can requeue it and a burst
        accumulates to full batches) instead of merely delaying the batch
        after extraction.  Ignored once the queue closes (drain proceeds);
        setters must call :meth:`poke` to wake the consumer.
        """
        max_batch = max(1, int(max_batch))
        with self._cv:
            while True:
                while not self._dq or (
                    gate is not None and not gate.is_set()
                    and not self._closed
                ):
                    if self._closed and not self._dq:
                        return None
                    self._cv.wait()
                cell = self._dq[0].cell
                deadline = time.monotonic() + max(0.0, float(window_s))
                while not self._closed:
                    if sum(1 for r in self._dq if r.cell == cell) >= max_batch:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                if not self._dq:
                    # drain_items emptied the queue while the batch window
                    # waited (a fleet drain requeuing this replica's work,
                    # round 18) — go back to blocking for fresh work.
                    continue
                if (
                    gate is not None and not gate.is_set()
                    and not self._closed
                ):
                    # pause() landed during the batch window: hold the
                    # work IN the queue (the documented pause contract —
                    # a drain can still requeue it) instead of extracting
                    # a batch for a paused dispatcher.
                    continue
                # One batching policy for the whole runtime: the head-seeded
                # same-cell selection lives in batching.form_batches.
                batch = form_batches(self._dq, max_batch)[0]
                taken = set(map(id, batch))
                self._dq = deque(r for r in self._dq if id(r) not in taken)
                return batch

    def poke(self) -> None:
        """Wake blocked consumers to re-check external state (the pause
        gate) — called by ``PartitionEngine.resume``."""
        with self._cv:
            self._cv.notify_all()

    def close(self) -> None:
        """Stop admissions; consumers drain the remainder then get None."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain_items(self) -> List:
        """Remove and return everything still queued (non-draining
        shutdown resolves these with :class:`EngineStoppedError`)."""
        with self._cv:
            items = list(self._dq)
            self._dq.clear()
            self._cv.notify_all()
            return items
