"""Bounded request queue with same-cell batch extraction.

The admission side is strict (``put`` raises :class:`QueueFullError` when
the bound is hit — the engine wraps it with a retry-after estimate) and the
consumer side pops *micro-batches*: the oldest request seeds a batch and
later requests from the same shape cell join it, up to ``max_batch``,
optionally waiting a short batch window for stragglers.  Requests from
other cells keep their FIFO order — extracting a batch never reorders the
remainder.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from .batching import form_batches
from .errors import EngineStoppedError, QueueFullError


class BoundedServeQueue:
    """Thread-safe bounded FIFO of items carrying a ``.cell`` attribute."""

    def __init__(self, bound: int):
        if bound < 1:
            raise ValueError("queue bound must be >= 1")
        self.bound = int(bound)
        self._dq: deque = deque()
        self._cv = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return len(self._dq)

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def put(self, item) -> None:
        """Admit one request; raises :class:`QueueFullError` at the bound
        and :class:`EngineStoppedError` after :meth:`close`."""
        with self._cv:
            if self._closed:
                raise EngineStoppedError("queue closed; engine is draining")
            if len(self._dq) >= self.bound:
                raise QueueFullError()
            self._dq.append(item)
            self._cv.notify_all()

    def pop_batch(self, max_batch: int, window_s: float = 0.0) -> Optional[List]:
        """Block until a request is available, then return a same-cell batch.

        The head request's cell seeds the batch; if fewer than ``max_batch``
        same-cell requests are queued, waits up to ``window_s`` for more to
        arrive before dispatching.  Returns ``None`` exactly once the queue
        is closed *and* drained (the graceful-shutdown termination signal).
        """
        max_batch = max(1, int(max_batch))
        with self._cv:
            while not self._dq:
                if self._closed:
                    return None
                self._cv.wait()
            cell = self._dq[0].cell
            deadline = time.monotonic() + max(0.0, float(window_s))
            while not self._closed:
                if sum(1 for r in self._dq if r.cell == cell) >= max_batch:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            # One batching policy for the whole runtime: the head-seeded
            # same-cell selection lives in batching.form_batches.
            batch = form_batches(self._dq, max_batch)[0]
            taken = set(map(id, batch))
            self._dq = deque(r for r in self._dq if id(r) not in taken)
            return batch

    def close(self) -> None:
        """Stop admissions; consumers drain the remainder then get None."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain_items(self) -> List:
        """Remove and return everything still queued (non-draining
        shutdown resolves these with :class:`EngineStoppedError`)."""
        with self._cv:
            items = list(self._dq)
            self._dq.clear()
            self._cv.notify_all()
            return items
