"""kaminpar_tpu.serve — the partition-serving runtime (ISSUE 3).

A :class:`PartitionEngine` owns one long-lived warm device context:
ladder/k-range warmup at startup, a bounded async request queue with
admission control, deadlines, and backpressure, micro-batching of
same-shape-cell requests with single-dispatch batched metrics, and a
structured stats snapshot.  ``python -m kaminpar_tpu.serve`` is the CLI
entry (serve files, run the synthetic demo load, or warm up and exit).
"""

from .batching import (
    PackedBatch,
    ShapeCell,
    batched_metrics,
    form_batches,
    pack_graphs,
    shape_cell,
    unpack_partition,
)
from .engine import PartitionEngine, ServeFuture, ServeRequest, ServeResult
from .fleet import FleetFuture, PartitionFleet
from .lanestack import LaneStackReport, LaneStackUnsupported, run_lanestacked
from .errors import (
    CapacityError,
    DeadlineExceededError,
    EngineStoppedError,
    QueueFullError,
    RequestCancelledError,
    ServeError,
)
from .queue import BoundedServeQueue
from .stats import ServeStats

__all__ = [
    "BoundedServeQueue",
    "CapacityError",
    "DeadlineExceededError",
    "EngineStoppedError",
    "FleetFuture",
    "LaneStackReport",
    "LaneStackUnsupported",
    "PackedBatch",
    "PartitionEngine",
    "PartitionFleet",
    "run_lanestacked",
    "QueueFullError",
    "RequestCancelledError",
    "ServeError",
    "ServeFuture",
    "ServeRequest",
    "ServeResult",
    "ServeStats",
    "ShapeCell",
    "batched_metrics",
    "form_batches",
    "pack_graphs",
    "shape_cell",
    "unpack_partition",
]
