"""The partition-serving engine: one long-lived warm device context.

``KaMinPar.compute_partition`` is a cold, single-graph, synchronous call —
the shape ladder, compile cache, and device workspaces are rebuilt per call
and idle between calls.  :class:`PartitionEngine` turns that machinery into
a persistent runtime, the standard inference-stack shape:

* **Warmup** — at startup the engine precompiles/warms the executable set
  over a configured shape-bucket ladder and k-range (one synthetic
  partition per (rung, k); every padded bucket the multilevel hierarchy
  visits below that rung gets traced and lands in the persistent XLA
  cache).  Per-cell warm cost is recorded from ``utils/compile_stats`` and
  exposed via :attr:`warmup_report` (the ``tools warmup`` subcommand prints
  it).
* **Bounded async queue** — ``submit`` performs admission control against
  a bounded queue and returns a :class:`ServeFuture`; a full queue rejects
  with a retry-after estimate (backpressure), per-request deadlines expire
  queued work, and ``shutdown(drain=True)`` drains gracefully.
* **Micro-batching** — requests in the same (node-bucket, edge-bucket, k)
  shape cell are dispatched as one batch: partitions are produced by the
  engine's warm pipeline per graph (bit-identical to sequential facade
  runs — asserted in tests/test_serve.py), then the whole batch's quality
  metrics are computed in a single dispatch over the packed disjoint-union
  buffer with one batched readback (serve/batching.py).

A synchronous convenience wrapper (:meth:`partition`) lets the facade
delegate to a warm engine (``KaMinPar(ctx, engine=...)``).
"""

from __future__ import annotations

import copy
import itertools
import threading
import time
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Union

import numpy as np

from ..context import Context, ServeContext
from .batching import ShapeCell, batched_metrics, pack_graphs, shape_cell
from .errors import (
    CapacityError,
    DeadlineExceededError,
    EngineStoppedError,
    QueueFullError,
    RequestCancelledError,
    ServeError,
)
from .queue import BoundedServeQueue
from .stats import ServeStats


@dataclass
class ServeResult:
    """What a fulfilled request resolves to."""

    partition: np.ndarray
    cut: int
    feasible: bool
    batch_size: int
    queue_wait_s: float
    execute_s: float
    warm_hit: bool
    request_id: int


class ServeFuture:
    """Completion handle for a submitted request."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._ev = threading.Event()
        self._result: Optional[ServeResult] = None
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._started = False
        # First-wins claim, distinct from the waiter event (round 19):
        # the finalization hook must run BETWEEN claiming the outcome and
        # releasing the waiter — a caller must never act on a result the
        # journal has not recorded — so _done claims under the lock,
        # _on_done fires outside it, and only then does _ev wake waiters.
        self._done = False
        self._lock = threading.Lock()
        # Finalization hook (round 19, serve/journal.py): invoked exactly
        # once — on the FIRST-WINS resolution/rejection, outside the lock
        # but BEFORE the waiter event — with (result | None, error |
        # None).  The engine points it at the journal's resolution
        # writer, so every terminal path (dispatcher, watchdog, deadline,
        # drain) journals through one funnel.
        self._on_done = None

    def cancel(self) -> bool:
        """Cancel if execution has not started; returns success.  A running
        XLA computation cannot be interrupted — late cancels return False."""
        with self._lock:
            if self._started or self._done:
                return False
            self._cancelled = True
        return True

    @property
    def cancelled(self) -> bool:
        with self._lock:
            return self._cancelled

    def _mark_started(self) -> bool:
        """Engine-side: claim the request for execution; False if it was
        cancelled first."""
        with self._lock:
            if self._cancelled:
                return False
            self._started = True
            return True

    def _resolve(self, result: ServeResult) -> bool:
        """First resolution wins (round 17): the execution watchdog may
        force-reject a hung batch's futures from its monitor thread; if
        the abandoned dispatch later returns, its late result is
        discarded here.  Returns whether THIS call resolved the future.

        The finalization hook fires BEFORE the waiter event (round 19):
        a journaled resolution must be durable before ``result()`` can
        return it (serve/journal.py durability contract)."""
        with self._lock:
            if self._done:
                return False
            self._done = True
            self._result = result
        self._fire_on_done(result, None)
        self._ev.set()
        return True

    def _reject(self, error: BaseException) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
            self._error = error
        self._fire_on_done(None, error)
        self._ev.set()
        return True

    def _fire_on_done(self, result, error) -> None:
        cb = self._on_done
        if cb is None:
            return
        try:
            cb(result, error)
        except Exception:  # noqa: BLE001 — a journaling failure must never
            pass           # un-resolve a finished request

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        """Block for the result; raises the request's error (deadline,
        cancellation, engine-stopped, or the pipeline's own exception)."""
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


@dataclass
class ServeRequest:
    """One queued unit of work (internal; carries the batching cell)."""

    id: int
    graph: object
    k: int
    epsilon: float
    cell: ShapeCell
    future: ServeFuture
    enqueue_t: float
    deadline_t: Optional[float]  # absolute monotonic; None = no deadline
    warm_hit: bool
    max_block_weights: Optional[Sequence[int]] = None
    min_epsilon: float = 0.0
    min_block_weights: Optional[Sequence[int]] = None
    # Quality tier (round 17): "strong" = the engine's full pipeline;
    # "fast" = the trimmed-refinement solver.  The quality_strong ->
    # quality_fast ladder rung demotes strong requests per shape cell
    # under capacity-class failures (counted, reversible).
    quality: str = "strong"
    # Request-scoped trace id (round 20, telemetry/reqtrace.py): minted at
    # submit (or inherited from the fleet / the journal on replay) and
    # carried for the request's whole life — one connected event chain per
    # request even across resteers and crash replays.
    trace_id: str = ""
    # Queue depth observed at admission (stamped by BoundedServeQueue.put;
    # rides the admit trace event).
    queue_position: int = 0
    # The tier that actually served the request ("" until dispatch; may
    # differ from ``quality`` under a quality_strong demotion) — warm
    # accounting is tier-keyed, because the two tiers compile different
    # executable sets.
    quality_served: str = ""
    # Filled during execution:
    partition: Optional[np.ndarray] = None
    caps: Optional[np.ndarray] = None
    execute_s: float = 0.0
    queue_wait_s: float = 0.0
    # Unamortized service cost feeding the retry-after EMA.  Lane-stacked
    # requests report execute_s = batch wall / occupancy (the latency
    # share), but the drain-rate estimate divides the EMA by max_batch
    # itself — feeding it the amortized share would double-count the batch
    # width.  None = use execute_s (the per-graph loop, where they agree).
    service_s: Optional[float] = None

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now > self.deadline_t


class PartitionEngine:
    """Persistent partition-serving runtime over one warm device context.

    Usage::

        from kaminpar_tpu.serve import PartitionEngine
        with PartitionEngine("serve") as engine:        # starts + warms
            fut = engine.submit(graph, k=8)             # async
            part = fut.result().partition
            part2 = engine.partition(graph2, k=8)       # sync wrapper

    Thread model: ``submit``/``partition`` are called from any thread; a
    single dispatcher thread owns the pipeline (batch formation, the warm
    facade, the packed metrics dispatch), so device work is never issued
    concurrently and per-request RNG streams stay deterministic.
    """

    def __init__(
        self,
        ctx: Union[Context, str, None] = None,
        name: str = "",
        **serve_overrides,
    ):
        from ..presets import create_context_by_preset_name

        # Replica tag (round 18, serve/fleet.py): names the dispatcher
        # thread (so per-replica trace lanes fall out of the trace
        # recorder's thread_name metadata) and prefixes log/warning text.
        self.name = str(name)

        if ctx is None:
            ctx = create_context_by_preset_name("serve")
        elif isinstance(ctx, str):
            ctx = create_context_by_preset_name(ctx)
        else:
            # The engine owns its tree: a caller mutating the context they
            # passed must not skew results of in-flight requests.
            ctx = copy.deepcopy(ctx)
        self.ctx = ctx
        if serve_overrides:
            ctx.serve = replace(ctx.serve, **serve_overrides)
        self.serve: ServeContext = ctx.serve
        # This engine OWNS its runtime settings (compilation cache, layout
        # build, sync timers): the runtime is activated thread-locally
        # around engine-side pipeline work (warmup, lane-stacked batches),
        # so engines with conflicting configs coexist in one process
        # (ISSUE 6; the internal facade activates its own equivalent
        # runtime around per-graph runs).
        from ..context import EngineRuntime

        self.runtime = EngineRuntime.from_parallel(ctx.parallel)
        lane_mode = str(getattr(self.serve, "lane_stack", "off")).strip().lower()
        if lane_mode not in ("auto", "on", "off"):
            raise ValueError(
                f"ServeContext.lane_stack {self.serve.lane_stack!r}: "
                "expected 'auto', 'on', or 'off'"
            )
        self._queue = BoundedServeQueue(self.serve.queue_bound)
        self.stats_ = ServeStats()
        # Request-scoped tracing + SLO burn accounting (round 20,
        # telemetry/{reqtrace,slo}.py).  A fleet replaces ``reqtrace`` with
        # one registry shared across its replicas so resteered requests
        # keep one connected event chain.  ``_slo`` is None unless the
        # ServeContext arms at least one objective.
        from ..telemetry.reqtrace import ReqTrace
        from ..telemetry.slo import BurnTracker

        self.reqtrace = ReqTrace()
        self._slo = BurnTracker.from_serve(self.serve)
        # (n_bucket, k, tier) — warm-hit accounting, keyed by the quality
        # tier that served the cell (the two tiers compile different
        # executable sets, so a fast-served cell is not warm for strong).
        self._warm_nk: set = set()
        self._warm_cells: set = set()  # exact (n_bucket, m_bucket, k) cells
        # Lane-stack shape keys THIS engine has already traced (warmup rows
        # or a served batch): (LaneStackReport.layout_key, k, epsilon).
        # Keying engine-locally keeps the warm-hit demotion in
        # _try_lanestacked from misfiring on compile events raised by OTHER
        # engines/facades in the process (the compile census is
        # process-global).
        self._warm_stack_keys: set = set()
        # Unified resilience layer (round 17, kaminpar_tpu/resilience):
        # this engine owns a private breaker registry for the serve-tier
        # ladder rungs — per-cell "lanestack" breakers (generalizing the
        # round-11 engine-global latch, now reversible via half-open
        # probing), per-cell "cell" breakers (a poisoned shape cell
        # fast-fails new admissions instead of wedging the queue), and
        # per-cell "quality_strong" breakers (capacity-class failures
        # demote strong requests to the fast tier).  Pipeline rungs
        # (lp_pallas, ip_device, device_decode) live on the process-global
        # registry.  The watchdog bounds hung executes.
        from ..resilience.breakers import BreakerRegistry
        from ..resilience.watchdog import ExecutionWatchdog

        self.resilience = ctx.resilience
        self.breakers = BreakerRegistry(
            threshold=self.resilience.breaker_threshold,
            cooldown_s=self.resilience.breaker_cooldown_s,
        )
        self.watchdog = ExecutionWatchdog(self.resilience.dossier_path)
        self.warmup_report: List[dict] = []
        # Warm-cache inheritance (round 18, serve/fleet.py): True once
        # inherit_warmup imported another replica's warm state — the
        # warmup passes then skip every inherited cell (and the aux
        # passes entirely: their executables are process-warm from the
        # source replica, and the shared persistent cache dir covers the
        # cross-process case).
        self._inherited = False
        # Requests currently being executed by the dispatcher (the bounded
        # shutdown force-resolves these when the worker dies mid-batch).
        self._inflight: List[ServeRequest] = []
        # Lazily-built trimmed-refinement solver serving quality="fast"
        # requests and quality_strong demotions.
        self._fast_solver = None
        # Whether THIS engine armed the process-wide fault plan (start()
        # arms, shutdown() disarms — injections must not outlive us).
        self._armed_faults = False
        # Admission-preflight ceiling (ISSUE 12): resolved lazily at start()
        # — explicit override > measured allocator limit > device-kind
        # table; None disables (no ceiling is knowable, e.g. CPU without
        # allocator stats).
        self._capacity_ceiling: Optional[int] = None
        self._device_kind: str = ""
        # Crash-safe journal (round 19, serve/journal.py): admitted
        # requests are journaled at admit and at first-wins resolution;
        # start() replays unresolved entries + restores the warm state.
        # Env KPTPU_SERVE_JOURNAL overrides (reaches child processes).
        import os as _os

        env_journal = _os.environ.get("KPTPU_SERVE_JOURNAL", "")
        if env_journal and self.name:
            # A fleet's replicas all see the same env var: suffix by the
            # engine name or N engines would interleave one journal file
            # with colliding request ids (the context-knob path gets its
            # per-replica suffix from the fleet constructor).
            env_journal += f".{self.name}"
        self._journal_path = env_journal or getattr(
            self.serve, "journal_path", ""
        )
        self._journal = None
        self._ids = itertools.count(1)
        self._solver = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._gate = threading.Event()  # pause/resume; set == dispatching
        self._gate.set()
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self, warmup: bool = True) -> "PartitionEngine":
        """Initialize the warm context (idempotent).  ``warmup=True`` runs
        the ladder precompile before the first request is accepted."""
        with self._lock:
            if self._running:
                return self
            if self._queue.closed:
                # Restart after shutdown: the old queue was closed to drain
                # the dispatcher, so a fresh one is needed (warm state —
                # solver caches, warm cells, stats — carries over).
                self._queue = BoundedServeQueue(self.serve.queue_bound)
            from ..kaminpar import KaMinPar

            # The internal facade owns an EngineRuntime built from the same
            # context, so its per-graph runs see this engine's settings
            # regardless of other engines in the process (ISSUE 6).
            if self._solver is None:
                self._solver = KaMinPar(copy.deepcopy(self.ctx))
            # Always track compile events (idempotent): the lane-stack
            # dispatch uses the census to keep warm-hit accounting honest
            # even on warmup=False engines.
            from ..utils import compile_stats

            compile_stats.enable_compile_time_tracking()
            if self.resilience.fault_plan:
                # Arm the context's chaos plan process-wide (seed-keyed, so
                # the run replays bit-for-bit); env KPTPU_FAULTS outranks
                # it by arming earlier via the lazy env discovery.  The
                # engine remembers that IT armed and disarms at shutdown —
                # chaos injections must not outlive the engine and leak
                # into unrelated engines/pipelines in the process.
                from ..resilience import faults

                if faults.active_plan() is None:
                    faults.arm(faults.FaultPlan.parse(
                        self.resilience.fault_plan,
                        seed=self.resilience.fault_seed,
                    ))
                    self._armed_faults = True
                else:
                    import warnings

                    warnings.warn(
                        "kaminpar_tpu serve: a fault plan is already "
                        "armed in this process — this engine's "
                        "resilience.fault_plan is ignored (one plan per "
                        "process; disarm the active one first).",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            recovery = None
            if self._journal_path and self._journal is None:
                # Crash recovery (round 19, serve/journal.py): parse the
                # journal BEFORE warmup — the warm-state record seeds the
                # warm sets through the PR 14 inheritance path, so
                # warmup below raises zero compile events for restored
                # cells; unresolved admits replay once the queue exists.
                from . import journal as _journal

                recovery = _journal.read_journal(self._journal_path)
                if recovery["max_id"]:
                    # Resume the id counter past the dead run's ids so a
                    # fresh admission can never collide with a journal
                    # entry awaiting replay.
                    self._ids = itertools.count(recovery["max_id"] + 1)
                if recovery["warm_state"] is not None:
                    _journal.apply_warm_state(self, recovery["warm_state"])
            try:
                self._resolve_capacity_ceiling()
                if warmup:
                    self._warmup()
            except BaseException:
                # start() failing after arming must not leak the chaos
                # plan into the process (shutdown's disarm is unreachable
                # for a never-running engine).
                self._disarm_faults()
                raise
            if recovery is not None:
                from ..utils.timer import scoped_timer
                from . import journal as _journal

                self._journal = _journal.ServeJournal(
                    self._journal_path,
                    fsync_every=self.serve.journal_fsync_every,
                )
                # Durable warm state as of THIS start (first runs write
                # their fresh warmup here; restarts refresh the record).
                self._journal.append(
                    _journal.warm_state_record(self), force_fsync=True
                )
                if recovery["unresolved"]:
                    with scoped_timer("journal_replay"):
                        self._replay_journal(recovery["unresolved"])
            self._running = True
            thread_name = "kaminpar-serve-dispatch" + (
                f"-{self.name}" if self.name else ""
            )
            self._thread = threading.Thread(
                target=self._loop, name=thread_name, daemon=True
            )
            self._thread.start()
        return self

    def _resolve_capacity_ceiling(self) -> None:
        """Resolve the admission-preflight ceiling (ISSUE 12): the explicit
        ServeContext override, else the device allocator's measured
        bytes_limit, else the per-device-kind HBM table
        (telemetry/capacity.py) — None when nothing is knowable."""
        from ..telemetry import capacity
        from ..utils import heap_profiler

        try:
            import jax

            self._device_kind = str(
                getattr(jax.devices()[0], "device_kind", "")
            )
        except Exception:  # noqa: BLE001 — a dead backend resolves later
            self._device_kind = ""
        explicit = int(getattr(self.serve, "capacity_ceiling_bytes", 0) or 0)
        if explicit > 0:
            self._capacity_ceiling = explicit
            return
        limit = heap_profiler.memory_summary().get("bytes_limit")
        if limit:
            # bytes_limit is already the allocator's usable pool (XLA's
            # reservation is taken off the top) — applying the planner's
            # headroom again would double-discount vs the device-kind
            # table path and HBM_BUDGET.md.
            self._capacity_ceiling = int(limit)
            return
        self._capacity_ceiling = capacity.device_ceiling_bytes(
            self._device_kind
        )

    def _run_preflight(self, graph, k: int) -> None:
        """The one capacity-preflight invocation (ISSUE 12): raises
        :class:`CapacityError` when the predicted watermark exceeds this
        engine's ceiling; returns silently when the preflight is off or
        no ceiling is knowable.  Shared by the counting admission path
        (:meth:`_capacity_preflight`) and the fleet router's non-counting
        steering probe (:meth:`capacity_verdict`) so the two can never
        diverge on what "fits" means."""
        mode = str(
            getattr(self.serve, "capacity_preflight", "auto")
        ).strip().lower()
        if mode == "off" or self._capacity_ceiling is None:
            return
        from ..telemetry import capacity
        from ..utils.timer import scoped_timer

        with scoped_timer("capacity_preflight"):
            capacity.preflight(
                graph, k,
                ceiling_bytes=self._capacity_ceiling,
                device_kind=self._device_kind,
                device_decode=(
                    self.ctx.compression.enabled
                    and str(self.ctx.compression.device_decode) != "off"
                ),
            )

    def _capacity_preflight(self, graph, k: int) -> None:
        """Reject a predicted-oversize request with :class:`CapacityError`
        BEFORE it is queued (and long before anything compiles) — pure
        host arithmetic over the graph's padded shape cell (ISSUE 12; the
        first piece of the ROADMAP serve-fleet SLO-aware admission)."""
        try:
            self._run_preflight(graph, k)
        except CapacityError:
            self.stats_.bump("rejected_capacity")
            from ..telemetry import trace as ttrace

            rec = ttrace.active()
            if rec is not None:
                rec.instant(
                    "serve.reject_capacity", k=int(k),
                    ceiling_bytes=self._capacity_ceiling,
                )
            raise

    def inherit_warmup(self, source: "PartitionEngine") -> None:
        """Import another replica's warm state (round 18 warm-cache
        inheritance): its warmup-report rows land here marked
        ``inherited=True`` with zero wall/compile cost, its warm cells /
        (n, k, tier) pairs / lane-stack layout keys seed this engine's
        warm-accounting sets, and its service-time EMA seeds the
        retry-after estimate.  A subsequent ``start(warmup=True)`` then
        skips every inherited cell — replica N+1 pays zero synthetic
        partitions for cells the fleet already traced (the compiled
        executables are shared in-process, and the shared persistent
        cache dir covers a fresh process).  Must be called before
        :meth:`start`; inherited-vs-local counts ride ``warmup_report``,
        ``stats()`` and the Prometheus exposition."""
        for row in source.warmup_report:
            inherited = dict(row)
            inherited["inherited"] = True
            # The cost was paid by the source replica, not this one.
            inherited["wall_s"] = 0.0
            inherited["backend_compile_s"] = 0.0
            inherited["trace_s"] = 0.0
            self.warmup_report.append(inherited)
        self._warm_cells |= source._warm_cells
        self._warm_nk |= source._warm_nk
        self._warm_stack_keys |= source._warm_stack_keys
        ema = source.stats_.service_time_estimate()
        if ema > 0.0:
            self.stats_.seed_service_time(ema)
        self._inherited = True

    def _warmup(self) -> None:
        """Trace/compile the executable set over warm_ladder x warm_ks by
        running one synthetic RMAT partition per cell; every padded bucket
        the hierarchy visits below each rung gets warmed too.  Per-cell
        wall + compile/trace seconds come from utils/compile_stats.
        Cells already imported via :meth:`inherit_warmup` are skipped —
        their inherited report rows are in place and the executables are
        warm from the source replica."""
        from ..graph.generators import rmat_graph
        from ..utils import compile_stats

        # ONE synthetic graph per rung, shared by every warm pass (the
        # rung-to-scale mapping lives here alone, so the passes cannot
        # drift).
        rung_graphs: dict = {}

        def rung_graph(n):
            if n not in rung_graphs:
                scale = max(2, int(np.ceil(np.log2(max(int(n), 4)))))
                rung_graphs[n] = (scale, rmat_graph(
                    scale, edge_factor=self.serve.warm_edge_factor, seed=1
                ))
            return rung_graphs[n]

        compile_stats.enable_compile_time_tracking()
        from ..resilience.errors import ResilienceError, classify
        from ..resilience.faults import maybe_inject

        try:
            # Named "warmup" injection point: a warmup-pass fault degrades
            # the engine to cold-start serving, never fails start().
            maybe_inject("warmup", site="engine_warmup")
        except ResilienceError as exc:
            self._warmup_fault(exc, "warmup pass")
            return
        for n in self.serve.warm_ladder:
            for k in self.serve.warm_ks:
                scale, g = rung_graph(n)
                if k > (1 << scale):
                    continue
                cell = shape_cell(g, k)
                if self._inherited and cell in self._warm_cells:
                    continue  # imported from the fleet — already traced
                before = compile_stats.compile_time_snapshot()
                t0 = time.perf_counter()
                try:
                    maybe_inject("compile", site=f"warmup_cell:{n}:{k}")
                    with self.watchdog.guard(
                        "warmup_compile", self.resilience.compile_timeout_s,
                        on_timeout=lambda d, c=cell: self._on_hang(c, d),
                    ):
                        self._solver.set_graph(g)
                        self._solver.compute_partition(int(k), 0.03)
                except Exception as exc:  # noqa: BLE001 — one poisoned warm
                    # cell must not abort the ladder; classify, count,
                    # keep warming the rest.
                    self._warmup_fault(
                        classify(exc, site=f"warmup_cell:{n}:{k}"),
                        f"warm cell (n={n}, k={k})",
                    )
                    continue
                wall = time.perf_counter() - t0
                after = compile_stats.compile_time_snapshot()
                row = {
                    "n": 1 << scale,
                    "k": int(k),
                    "n_bucket": cell.n_bucket,
                    "m_bucket": cell.m_bucket,
                    "wall_s": round(wall, 3),
                    "backend_compile_s": round(
                        after["backend_compile_s"] - before["backend_compile_s"], 3
                    ),
                    "trace_s": round(after["trace_s"] - before["trace_s"], 3),
                }
                if compile_stats.executable_census_armed():
                    # Executable census of the cell (ISSUE 12): what the
                    # warmed hot kernels WOULD do on silicon — flops/bytes
                    # from cost_analysis, arg/out/temp/peak bytes from
                    # memory_analysis — via shape-only lowering (no device
                    # data, zero transfers; armed-only so unarmed warmups
                    # pay nothing).
                    census_row = self._harvest_cell_census(cell)
                    if census_row:
                        row["census"] = census_row
                self.warmup_report.append(row)
                self._note_warm(cell)
        if not self._inherited:
            # Inherited engines skip the aux passes: the ip-pool /
            # lane-stack / compressed executables are process-warm from
            # the source replica's passes (and rode the inherited report
            # rows above); re-running them per replica would pay the
            # synthetic partitions N times for one executable set.
            self._warm_ip_pool(rung_graph)
            self._warm_lanestack(rung_graph)
            self._warm_compressed(rung_graph)
        # Seed the retry-after service-time EMA from the warm execution
        # cost (wall minus compile/trace — the steady-state share) so the
        # very first admission rejects carry a real estimate instead of
        # the blind floor (ISSUE 6 satellite).  Inherited rows carry zero
        # wall (the source paid it) and must not dilute the mean — the
        # inherit path seeds the EMA from the source's instead.
        execs = [
            max(r["wall_s"] - r["backend_compile_s"] - r["trace_s"], 1e-3)
            for r in self.warmup_report
            if "kind" not in r and not r.get("inherited")
        ]
        if execs:
            self.stats_.seed_service_time(float(np.mean(execs)))

    def _warmup_fault(self, err, what: str) -> None:
        """Count + surface one contained warmup failure (typed; the engine
        serves cold-start for whatever was not warmed)."""
        import warnings

        self.stats_.bump("warmup_faults")
        warnings.warn(
            f"kaminpar_tpu serve: {what} failed during warmup "
            f"({err.failure_class}: {err}) — continuing; unwarmed cells "
            "pay their compile on first request.",
            RuntimeWarning,
            stacklevel=3,
        )

    def _on_hang(self, cell: ShapeCell, dossier: dict,
                 live: Optional[List[ServeRequest]] = None) -> None:
        """Watchdog timeout callback (monitor thread): convert the hang
        into a breaker trip + typed future resolutions instead of a
        killed process (round 17 tentpole d).  The hung dispatch itself
        is abandoned — the idempotent futures discard its late result."""
        from ..resilience.errors import ExecuteFault

        self.stats_.bump("watchdog_timeouts")
        key = (cell.n_bucket, cell.m_bucket, cell.k)
        # Force the trip (not one counted failure): each further probe of
        # a hung cell wedges the single dispatcher thread for a full
        # deadline — one observed hang is conclusive, the next request
        # fast-fails with PoisonedCell until the cooldown's half-open
        # probe.
        self.breakers.get("cell", key).trip()
        for req in (live or []):
            if req.future._reject(ExecuteFault(
                f"request {req.id} abandoned: {dossier['phase']} exceeded "
                f"the {dossier['timeout_s']}s watchdog deadline in cell "
                f"{key} (dossier on engine.stats()['resilience'])",
                site="watchdog",
            )):
                # Watchdog faults are resteerable (site="watchdog") — the
                # trace chain continues if the fleet re-homes the request.
                self._trace_event(req, "error", final=False,
                                  failure_class="worker-hung",
                                  site="watchdog")
                self.stats_.record_request(
                    time.monotonic() - req.enqueue_t, 0.0, failed=True
                )

    def _harvest_cell_census(self, cell: ShapeCell) -> dict:
        """Harvest the executable census of one warm shape cell via the
        capacity planner's shared ``capacity_contraction|n,m`` registry key
        (telemetry/capacity.harvest_contraction_cell) — the transient
        dominator lowered + compiled from ``jax.ShapeDtypeStruct`` shapes,
        so the planner and the warmup reuse each other's rows and one
        executable is never compiled twice.  Pure host-side compiler
        introspection — no device arrays exist, so the armed census adds
        zero blocking transfers and zero collectives (asserted in
        tests/test_capacity.py)."""
        from ..telemetry import capacity

        with self.runtime.activate():
            row = capacity.harvest_contraction_cell(
                int(cell.n_bucket), int(cell.m_bucket)
            )
        if not row:
            return {}
        return {
            k: row[k]
            for k in ("flops", "bytes_accessed", "temp_bytes", "peak_bytes")
            if row.get(k) is not None
        }

    def _warm_lanestack(self, rung_graph) -> None:
        """Precompile the lane-stacked pipeline per (rung, k, lane-count)
        cell (``serve.warm_lanes``; kind="lanestack" report rows, printed
        by ``tools warmup``).  Runs L copies of the rung's synthetic graph
        (``rung_graph`` — _warmup's memoized per-rung generator) through
        serve/lanestack.py — identical hierarchies, so the whole stack
        stays one cohort and every vmapped kernel of the lockstep pipeline
        gets traced at lane count L."""
        if self._lane_stack_mode() == "off" or not self.serve.warm_lanes:
            return
        from ..utils import compile_stats
        from .lanestack import LaneStackUnsupported, run_lanestacked

        for n in self.serve.warm_ladder:
            scale, g = rung_graph(n)
            for k in self.serve.warm_ks:
                if k < 2 or k > (1 << scale):
                    continue  # per-cell envelope bound, not config-wide
                for lanes in self.serve.warm_lanes:
                    before = compile_stats.compile_time_snapshot()
                    t0 = time.perf_counter()
                    try:
                        with self.runtime.activate():
                            _, rep = run_lanestacked(
                                self._solver.ctx, [g] * int(lanes), int(k), 0.03
                            )
                    except LaneStackUnsupported:
                        return  # config outside the envelope: nothing to warm
                    self._warm_stack_keys.add(
                        (rep.layout_key, int(k), 0.03)
                    )
                    wall = time.perf_counter() - t0
                    after = compile_stats.compile_time_snapshot()
                    cell = shape_cell(g, int(k))
                    self.warmup_report.append({
                        "kind": "lanestack",
                        "n": 1 << scale,
                        "k": int(k),
                        "n_bucket": cell.n_bucket,
                        "m_bucket": cell.m_bucket,
                        "lanes": int(lanes),
                        "wall_s": round(wall, 3),
                        "backend_compile_s": round(
                            after["backend_compile_s"]
                            - before["backend_compile_s"], 3
                        ),
                        "trace_s": round(
                            after["trace_s"] - before["trace_s"], 3
                        ),
                    })

    def _warm_compressed(self, rung_graph) -> None:
        """Trace/compile the decode-fused compressed-stream kernels per
        warm rung (ISSUE 10 satellite; ``kind="compressed"`` report rows,
        printed by ``tools warmup``).  Engines serving a terapart-style
        context (compression enabled with device decode routed on) warm
        the compressed LP sweep cell of every rung so the first real
        compressed request starts backend-compile-warm; other engines
        skip the pass entirely."""
        from ..graph.compressed import compress
        from ..graph.device_compressed import (
            DeviceCompressedView,
            device_decode_eligible,
            resolve_device_decode,
        )

        if not self.ctx.compression.enabled:
            return
        if resolve_device_decode(self.ctx.compression) == "off":
            return
        from ..coarsening.lp_clusterer import LPClustering
        from ..utils import compile_stats

        for n in self.serve.warm_ladder:
            _, g = rung_graph(n)
            cg = compress(g)
            # Same envelope gate the pipeline applies: an engine whose
            # requests will be routed dense (64-bit build, HEM clusterer)
            # must not burn warmup compiles on kernels it can never use.
            if not device_decode_eligible(self.ctx, cg)[0]:
                return
            before = compile_stats.compile_time_snapshot()
            t0 = time.perf_counter()
            with self.runtime.activate():
                cv = DeviceCompressedView(
                    cg, layout_mode=self.ctx.parallel.device_layout_build,
                )
                clusterer = LPClustering(self.ctx.coarsening.lp, 1)
                labels = clusterer.compute_clustering(
                    cv, max_cluster_weight=1 << 20
                )
                # Force execution so wall_s covers compile + run: ONE tiny
                # counted readback (warmup is outside the pipeline spine;
                # device code must not block_until_ready).
                from ..utils import sync_stats

                sync_stats.pull(labels[:1])
            wall = time.perf_counter() - t0
            after = compile_stats.compile_time_snapshot()
            self.warmup_report.append({
                "kind": "compressed",
                "n": int(n),
                "k": 0,  # clustering cell — no block count
                "n_bucket": cv.n_pad,
                "m_bucket": cv.m_pad,
                "wall_s": round(wall, 3),
                "backend_compile_s": round(
                    after["backend_compile_s"] - before["backend_compile_s"], 3
                ),
                "trace_s": round(after["trace_s"] - before["trace_s"], 3),
            })

    def _warm_ip_pool(self, rung_graph) -> None:
        """Precompile the lane-vmapped initial-bipartitioning pool per
        (n-bucket, m-bucket, lane-count) cell (ISSUE 4 satellite).  The
        synthetic warmup partitions above already trace the cells they
        visit; this pass AOT-compiles the k=2 bisection cell of every rung
        bucket explicitly — including the lane counts the adaptive
        repetition rule picks for each warm k — so the first real bisection
        in a cell starts backend-compile-warm (``rung_graph`` is _warmup's
        memoized per-rung generator, so this pass warms the exact cell the
        pipeline pass used).  Device backend only: the host pool has
        nothing to compile."""
        from ..initial.bipartitioner import resolve_ip_backend
        from ..ops import bipartition as bip

        ipc = self.ctx.initial_partitioning
        if resolve_ip_backend(ipc) != "device":
            return
        from ..utils import compile_stats

        # Recursive bisection halves final_k per level (k, ceil(k/2), ...,
        # 2), and each final_k maps to its own lane layout through the
        # adaptive repetition rule — warm the whole chain, not just the top.
        lane_layouts = set()
        for k in (2, *self.serve.warm_ks):
            k = int(k)
            while k > 1:
                lane_layouts.add(bip.method_lane_counts(ipc, k)[0])
                k = (k + 1) // 2 if k > 2 else 1
        for n in self.serve.warm_ladder:
            # The cell a rung's first bisection actually hits: the padded
            # buckets of the same synthetic graph the warmup partitions
            # above use (an m-bucket estimated from the edge factor can
            # land one ladder rung off the real graph's).
            pv = rung_graph(n)[1].padded()
            n_pad, m_pad = pv.n_pad, pv.m_pad
            for methods in sorted(lane_layouts):
                before = compile_stats.compile_time_snapshot()
                # Activate the engine's runtime so these compiles land in
                # ITS persistent cache dir (like the pipeline warm pass and
                # _warm_lanestack), not whatever dir is currently applied
                # process-wide.
                with self.runtime.activate():
                    wall = bip.warm_pool_executable(
                        n_pad, m_pad, methods, ipc.fm_num_iterations
                    )
                after = compile_stats.compile_time_snapshot()
                self.warmup_report.append({
                    "kind": "ip_pool",
                    "n": int(n),
                    "k": 2,
                    "n_bucket": n_pad,
                    "m_bucket": m_pad,
                    "lanes": sum(cnt for _, cnt in methods),
                    "wall_s": round(wall, 3),
                    "backend_compile_s": round(
                        after["backend_compile_s"] - before["backend_compile_s"], 3
                    ),
                    "trace_s": round(after["trace_s"] - before["trace_s"], 3),
                })

    def _note_warm(self, cell: ShapeCell, tier: str = "strong") -> None:
        self._warm_cells.add(cell)
        self._warm_nk.add((cell.n_bucket, cell.k, tier))

    @property
    def running(self) -> bool:
        return self._running

    def pause(self) -> None:
        """Hold the dispatcher (maintenance window; queued work waits IN
        the queue — where a fleet drain can requeue it — and admission
        stays open up to the queue bound).  Takes effect before the next
        batch is *extracted*: the gate-aware pop leaves work queued, so a
        paused burst accumulates to full batches."""
        self._gate.clear()

    def resume(self) -> None:
        self._gate.set()
        self._queue.poke()

    def shutdown(self, drain: bool = True, timeout_s: Optional[float] = None) -> None:
        """Stop the engine.  ``drain=True`` serves everything already
        queued first; ``drain=False`` rejects queued work with
        :class:`EngineStoppedError`.  Idempotent.

        Round 17 satellite: the drain is BOUNDED — if the dispatcher
        thread dies or hangs mid-batch, everything still unresolved
        (queued + in-flight) is force-resolved with a typed
        :class:`~kaminpar_tpu.resilience.errors.WorkerHung` after
        ``timeout_s`` (default ``ServeContext.drain_timeout_s``) instead
        of blocking callers forever."""
        with self._lock:
            if not self._running:
                return
            self._queue.close()
            if not drain:
                for req in self._queue.drain_items():
                    self.stats_.bump("cancelled")
                    req.future._reject(
                        EngineStoppedError("engine shut down before execution")
                    )
            self._gate.set()
            thread = self._thread
        if thread is not None:
            # `is not None`, not truthiness: an explicit timeout_s=0.0
            # means "force-resolve immediately", not "use the default".
            budget = (
                timeout_s if timeout_s is not None
                else self.serve.drain_timeout_s
            )
            thread.join(budget)
            if thread.is_alive():
                # The worker is hung (or wedged on a poisoned batch): the
                # drain contract still holds — every outstanding future is
                # resolved, with a typed error naming the cause.
                from ..resilience.errors import WorkerHung

                stuck = list(self._queue.drain_items())
                with self._lock:
                    stuck.extend(self._inflight)
                hung = 0
                for req in stuck:
                    if req.future._reject(WorkerHung(
                        f"request {req.id} unresolved: the dispatcher "
                        "thread did not finish draining within "
                        f"{budget}s "
                        "(worker dead or hung mid-batch)",
                        site="shutdown",
                    )):
                        hung += 1
                        self.stats_.record_request(
                            time.monotonic() - req.enqueue_t, 0.0, failed=True
                        )
                if hung:
                    self.stats_.bump("worker_hung", hung)
        # Final warm-state record + journal close (fsynced): a clean
        # shutdown leaves zero unresolved entries — EngineStopped/
        # WorkerHung force-resolutions above deliberately stay
        # UNRESOLVED in the journal so a restart replays them.
        self._close_journal()
        self._disarm_faults()
        with self._lock:
            self._running = False

    def _disarm_faults(self) -> None:
        """Disarm the process-wide fault plan iff THIS engine armed it."""
        if self._armed_faults:
            from ..resilience import faults

            faults.disarm()
            self._armed_faults = False

    # -- crash-safe journal (round 19, serve/journal.py) -------------------

    def _journal_admit(self, req: ServeRequest) -> None:
        """Journal one accepted request (admit record: params + graph
        payload, ONE counted bulk pull under ``journal_write``).  The
        future's resolution hook is installed by the submit path BEFORE
        the queue insert — a dispatcher racing ahead of this append just
        writes the resolve record first, which read_journal tolerates."""
        from ..utils.timer import scoped_timer
        from . import journal as _journal

        with scoped_timer("journal_write"):
            record = {
                "t": "admit",
                "id": req.id,
                "k": req.k,
                "epsilon": req.epsilon,
                "quality": req.quality,
                "min_epsilon": req.min_epsilon,
                "max_block_weights": (
                    None if req.max_block_weights is None
                    else [int(x) for x in req.max_block_weights]
                ),
                "min_block_weights": (
                    None if req.min_block_weights is None
                    else [int(x) for x in req.min_block_weights]
                ),
                # Trace continuity across crashes (round 20): replay
                # re-binds the replayed request to this id, so the
                # restarted process extends the SAME event chain.
                "trace_id": req.trace_id,
                "graph": _journal.encode_graph(req.graph),
            }
            self._journal.append(record)

    def _journal_resolution(self, jid: int, result, error) -> None:
        """Append the terminal record of journal entry ``jid`` — except
        for "the engine gave it back" classes (EngineStoppedError /
        WorkerHung), which leave the entry unresolved so a restart
        replays it (losing accepted work is the one thing the journal
        exists to prevent)."""
        jr = self._journal
        if jr is None:
            return
        if error is not None:
            from ..resilience.errors import WorkerHung

            if isinstance(error, (EngineStoppedError, WorkerHung)):
                return
            record = {
                "t": "resolve", "id": jid, "ok": 0,
                "error": getattr(
                    error, "failure_class", type(error).__name__
                ),
            }
        else:
            record = {
                "t": "resolve", "id": jid, "ok": 1,
                "cut": int(result.cut), "feasible": int(result.feasible),
            }
        jr.append(record, force_fsync=True)
        self.stats_.bump("journal_resolutions")

    def _replay_journal(self, entries) -> None:
        """Re-enqueue the journal's unresolved admits idempotently: each
        replayed request keeps its ORIGINAL journal id for the resolution
        record (no second admit record is written), runs without a
        deadline (the original deadline died with its process), and
        bypasses the admission bound — the work was admitted once
        already.  Decode is host->device puts only (zero pulls)."""
        from . import journal as _journal

        now = time.monotonic()
        for entry in entries:
            try:
                graph = _journal.decode_graph(
                    entry["graph"],
                    use_64bit=bool(self.ctx.use_64bit_ids),
                    layout_mode=self.ctx.parallel.device_layout_build,
                )
            except (KeyError, ValueError) as exc:
                import warnings

                warnings.warn(
                    f"kaminpar_tpu serve: journal entry {entry.get('id')} "
                    f"unreplayable ({type(exc).__name__}: {exc}) — skipped",
                    RuntimeWarning,
                    stacklevel=2,
                )
                continue
            cell = shape_cell(graph, int(entry["k"]))
            quality = str(entry.get("quality", "strong"))
            req = ServeRequest(
                id=next(self._ids),
                graph=graph,
                k=int(entry["k"]),
                epsilon=float(entry["epsilon"]),
                cell=cell,
                future=ServeFuture(0),
                enqueue_t=now,
                deadline_t=None,
                warm_hit=(cell.n_bucket, int(entry["k"]), quality)
                in self._warm_nk,
                max_block_weights=entry.get("max_block_weights"),
                min_epsilon=float(entry.get("min_epsilon", 0.0) or 0.0),
                min_block_weights=entry.get("min_block_weights"),
                quality=quality,
                trace_id=str(entry.get("trace_id", "") or ""),
            )
            req.future.request_id = req.id
            req.future._on_done = (
                lambda result, error, _id=int(entry["id"]):
                    self._journal_resolution(_id, result, error)
            )
            # Trace continuity (round 20): re-bind the journaled trace id
            # (minting a fresh one only for pre-round-20 journals) under
            # BOTH the new engine id and the original journal id, record a
            # replayed admit + an explicit journal_replay hop — the
            # restarted process extends the same event chain the dead one
            # started, so explain() shows admit -> replay -> resolution
            # connected.
            if not req.trace_id:
                req.trace_id = self.reqtrace.mint()
            self.reqtrace.bind(req.id, req.trace_id)
            self.reqtrace.bind(int(entry["id"]), req.trace_id)
            self.reqtrace.record(
                req.trace_id, "admit", request_id=req.id,
                engine=self.name, k=req.k, quality=quality,
                replayed=True, journal_id=int(entry["id"]),
            )
            self.reqtrace.record(
                req.trace_id, "journal_replay", request_id=req.id,
                engine=self.name, journal_id=int(entry["id"]),
            )
            self.stats_.record_warm(req.warm_hit)
            self._queue.put(req, force=True)
            self.stats_.bump("journal_replayed")

    def journal_mark_resteered(self, request_id: int) -> None:
        """Resolve journal entry ``request_id`` as re-homed (round 19):
        the fleet drain successfully requeued this request on a sibling
        replica, whose own journal now owns it — leaving the entry
        unresolved here would make a later revival of this slot replay
        work that already completed elsewhere."""
        jr = self._journal
        if jr is None:
            return
        jr.append(
            {"t": "resolve", "id": int(request_id), "ok": 0,
             "error": "resteered"},
            force_fsync=True,
        )
        self.stats_.bump("journal_resolutions")

    def _close_journal(self) -> None:
        jr = self._journal
        if jr is None:
            return
        from . import journal as _journal

        try:
            jr.append(_journal.warm_state_record(self), force_fsync=True)
        finally:
            jr.close()
            self._journal = None
        try:
            # Clean shutdown compacts the history down to what recovery
            # needs (unresolved admits + the final warm state): an
            # append-only file would otherwise grow one graph payload
            # per request forever and tax every restart's parse.
            _journal.compact(jr.path)
        except OSError as exc:
            import warnings

            warnings.warn(
                f"kaminpar_tpu serve: journal compaction failed "
                f"({exc}); the full history remains valid",
                RuntimeWarning,
                stacklevel=2,
            )

    def __enter__(self) -> "PartitionEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- request path ------------------------------------------------------

    def submit(
        self,
        graph,
        k: int,
        epsilon: float = 0.03,
        *,
        deadline_ms: Optional[float] = None,
        max_block_weights: Optional[Sequence[int]] = None,
        min_epsilon: float = 0.0,
        min_block_weights: Optional[Sequence[int]] = None,
        quality: str = "strong",
        trace_id: str = "",
    ) -> ServeFuture:
        """Enqueue one partition request; returns a :class:`ServeFuture`.

        Raises :class:`EngineStoppedError` when not running,
        :class:`QueueFullError` (with ``retry_after_s``) when admission
        control rejects the request, and
        :class:`~kaminpar_tpu.resilience.errors.PoisonedCell` (with
        ``retry_after_s``) when the request's shape cell tripped its
        circuit breaker — a deterministically failing cell fast-fails at
        admission instead of wedging the queue (round 17).

        ``quality``: "strong" (the engine's full pipeline) or "fast"
        (trimmed refinement — the tiered-SLO knob; strong requests can be
        demoted per cell by the quality_strong ladder rung under
        capacity-class failures).

        ``trace_id``: request-scoped trace id (round 20) — the fleet
        passes the id it minted at steer time so the engine extends the
        same event chain; direct callers leave it empty and the engine
        mints one (queryable via :meth:`explain`)."""
        if quality not in ("strong", "fast"):
            raise ValueError(
                f"quality must be 'strong' or 'fast', got {quality!r}"
            )
        if not self._running:
            raise EngineStoppedError("engine not started (call start())")
        self.stats_.bump("submitted")
        from ..resilience.errors import PoisonedCell
        from ..resilience.faults import maybe_inject

        tid = str(trace_id) or self.reqtrace.mint()
        maybe_inject("queue-admit", site="submit")
        try:
            self._capacity_preflight(graph, k)
        except CapacityError:
            self.reqtrace.record(tid, "reject", engine=self.name,
                                 reason="capacity")
            if self._slo is not None:
                self._slo.record_reject(capacity=True)
            raise
        cell = shape_cell(graph, k)
        cell_key = (cell.n_bucket, cell.m_bucket, cell.k)
        cell_breaker = self.breakers.get("cell", cell_key)
        if not cell_breaker.allow():
            # Poisoned cell: reject fast with the cooldown as the retry
            # hint; the post-cooldown half-open probe re-admits ONE
            # request, and its success restores the cell.
            self.stats_.bump("rejected_poisoned")
            self.reqtrace.record(tid, "reject", engine=self.name,
                                 reason="poisoned")
            raise PoisonedCell(
                cell_key, cell_breaker.retry_after_s(), site="submit"
            )
        warm = (cell.n_bucket, int(k), quality) in self._warm_nk
        self.stats_.record_warm(warm)
        if deadline_ms is None:
            deadline_ms = self.serve.default_deadline_ms
        now = time.monotonic()
        req = ServeRequest(
            id=next(self._ids),
            graph=graph,
            k=int(k),
            epsilon=float(epsilon),
            cell=cell,
            future=ServeFuture(0),
            enqueue_t=now,
            deadline_t=now + deadline_ms / 1e3 if deadline_ms else None,
            warm_hit=warm,
            max_block_weights=max_block_weights,
            min_epsilon=float(min_epsilon),
            min_block_weights=min_block_weights,
            quality=quality,
            trace_id=tid,
        )
        req.future.request_id = req.id
        from ..telemetry import trace as ttrace

        rec = ttrace.active()
        if self._journal is not None:
            # Install the resolution funnel BEFORE the queue insert: the
            # dispatcher may resolve the request the instant it is
            # queued, and a first-wins finalization racing ahead of the
            # hook would leave the entry unresolved forever (replayed as
            # duplicate work on every restart).  A resolve record landing
            # before its admit record is fine — read_journal matches by
            # id, not by order.
            req.future._on_done = (
                lambda result, error, _id=req.id:
                    self._journal_resolution(_id, result, error)
            )
        try:
            self._queue.put(req)
        except QueueFullError:
            if self._journal is not None:
                req.future._on_done = None  # never admitted: nothing to log
            self.stats_.bump("rejected_full")
            retry_after = self.stats_.retry_after_estimate(
                len(self._queue), self.serve.max_batch
            )
            self.reqtrace.record(tid, "reject", engine=self.name,
                                 reason="queue_full",
                                 retry_after_s=round(retry_after, 3))
            if self._slo is not None:
                self._slo.record_reject(capacity=False)
            if rec is not None:
                rec.instant("serve.reject", request_id=req.id,
                            retry_after_s=round(retry_after, 3))
            raise QueueFullError(retry_after) from None
        self.stats_.bump("admitted")
        self.reqtrace.bind(req.id, tid)
        self.reqtrace.record(
            tid, "admit", request_id=req.id, engine=self.name, k=req.k,
            n_bucket=cell.n_bucket, m_bucket=cell.m_bucket, warm_hit=warm,
            quality=quality, queue_position=req.queue_position,
        )
        if self._journal is not None:
            # Admitted => journaled: from here on, the only ways out of
            # the journal are a resolution record or a replay after
            # restart (serve/journal.py).
            self._journal_admit(req)
        if rec is not None:
            # Queue lifecycle point: admission (the matching dispatch/resolve
            # events come from the dispatcher thread's batch span).
            rec.instant("serve.admit", request_id=req.id, k=req.k,
                        n_bucket=cell.n_bucket, m_bucket=cell.m_bucket,
                        warm_hit=warm)
            rec.counter("serve.queue", {"depth": len(self._queue)})
        return req.future

    def partition(
        self,
        graph,
        k: int,
        epsilon: float = 0.03,
        *,
        deadline_ms: Optional[float] = None,
        max_block_weights: Optional[Sequence[int]] = None,
        min_epsilon: float = 0.0,
        min_block_weights: Optional[Sequence[int]] = None,
        quality: str = "strong",
    ) -> np.ndarray:
        """Synchronous convenience wrapper: submit + wait, returning the
        (n,) block array — the facade delegates here when constructed with
        an engine.  Auto-starts a not-yet-started engine *without* warmup
        (call :meth:`start` yourself to pay warmup at a chosen moment)."""
        if not self._running:
            self.start(warmup=False)
        fut = self.submit(
            graph, k, epsilon,
            deadline_ms=deadline_ms,
            max_block_weights=max_block_weights,
            min_epsilon=min_epsilon,
            min_block_weights=min_block_weights,
            quality=quality,
        )
        return fut.result().partition

    # -- request tracing (round 20, telemetry/reqtrace.py) -----------------

    def _final_error(self, error) -> bool:
        """Whether a typed failure terminates the request's trace chain.
        The "engine gave it back" classes (EngineStoppedError, WorkerHung,
        watchdog/shutdown ExecuteFault) are resteerable or replayable —
        the chain continues on a sibling replica or after restart."""
        from ..resilience.errors import ExecuteFault, WorkerHung

        if isinstance(error, (EngineStoppedError, WorkerHung)):
            return False
        return not (
            isinstance(error, ExecuteFault)
            and getattr(error, "site", "") in ("watchdog", "shutdown")
        )

    def _trace_event(self, req: ServeRequest, event: str,
                     final: bool = False, **fields) -> None:
        """Record one request-trace event (pure host dict append).  On a
        terminal event (``final=True``) the request's whole chain is
        rendered onto a per-request lane of the active Chrome trace."""
        tid = req.trace_id
        if not tid:
            return
        if event in ("resolve", "error"):
            fields["final"] = bool(final)
        self.reqtrace.record(tid, event, request_id=req.id,
                             engine=self.name, **fields)
        if final:
            from ..telemetry import trace as ttrace

            rec = ttrace.active()
            if rec is not None:
                from ..utils.timer import scoped_timer

                with scoped_timer("reqtrace_export"):
                    self.reqtrace.export_chrome(rec, tid)

    def explain(self, request_id: int) -> Optional[dict]:
        """Structured dossier for one request: its time-ordered trace
        event chain (admit, dispatch, lane-stack cohort, demotion,
        resolve/error, journal replay ...) plus a connectivity verdict —
        ``None`` for unknown/evicted ids.  Pure host work (counted under
        ``reqtrace_export``; a device pull here is a contract
        violation)."""
        from ..utils.timer import scoped_timer

        with scoped_timer("reqtrace_export"):
            return self.reqtrace.explain_request(int(request_id))

    # -- dispatcher --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            self._gate.wait()
            batch = self._queue.pop_batch(
                self.serve.max_batch, self.serve.batch_window_ms / 1e3,
                gate=self._gate,
            )
            if batch is None:
                return  # closed + drained: graceful exit
            try:
                self._execute_batch(batch)
            except Exception as exc:  # noqa: BLE001 — a poisoned batch must
                # not kill the dispatcher; classify the failure (round 17
                # taxonomy) and reject its requests with the typed error.
                from ..resilience.errors import classify

                err = classify(exc, site="dispatch")
                if batch:
                    key = (
                        batch[0].cell.n_bucket, batch[0].cell.m_bucket,
                        batch[0].cell.k,
                    )
                    self.breakers.get("cell", key).record_failure()
                for req in batch:
                    if req.future._reject(err):
                        self._trace_event(
                            req, "error",
                            final=self._final_error(err),
                            failure_class=getattr(
                                err, "failure_class", type(err).__name__
                            ),
                            site="dispatch",
                        )
                        wait = time.monotonic() - req.enqueue_t
                        self.stats_.record_request(wait, 0.0, failed=True)
                        if self._slo is not None:
                            self._slo.record_request(
                                req.quality, wait, ok=False
                            )

    def _execute_batch(self, batch: List[ServeRequest]) -> None:
        now = time.monotonic()
        live: List[ServeRequest] = []
        for req in batch:
            if req.future.cancelled:
                self.stats_.bump("cancelled")
                self._trace_event(req, "error", final=True,
                                  failure_class="cancelled")
                req.future._reject(RequestCancelledError(f"request {req.id}"))
            elif req.expired(now):
                self.stats_.bump("timed_out")
                wait = now - req.enqueue_t
                self._trace_event(req, "error", final=True,
                                  failure_class="deadline",
                                  queue_wait_ms=round(wait * 1e3, 1))
                if self._slo is not None:
                    self._slo.record_request(req.quality, wait, ok=False)
                req.future._reject(DeadlineExceededError(
                    f"request {req.id} expired after "
                    f"{(now - req.enqueue_t) * 1e3:.1f}ms in queue"
                ))
            elif req.future._mark_started():
                live.append(req)
            else:
                self.stats_.bump("cancelled")
                self._trace_event(req, "error", final=True,
                                  failure_class="cancelled")
                req.future._reject(RequestCancelledError(f"request {req.id}"))
        if not live:
            return
        self.stats_.record_batch(len(live))
        for req in live:
            # Batch-join lifecycle point: this request dispatches as part
            # of a formed micro-batch (occupancy = the lane axis).
            self._trace_event(req, "dispatch", occupancy=len(live))
        from ..telemetry import trace as ttrace

        rec = ttrace.active()
        if rec is not None:
            cell = live[0].cell
            rec.begin("serve.batch", occupancy=len(live), k=cell.k,
                      n_bucket=cell.n_bucket, m_bucket=cell.m_bucket)

        with self._lock:
            self._inflight = list(live)
        try:
            # Execution watchdog (round 17): a hung compile/execute inside
            # this batch has its futures force-resolved with a typed
            # ExecuteFault and its cell breaker tripped after
            # resilience.execute_timeout_s (0 disarms) — the dispatch is
            # abandoned, not cancelled, and its late result discarded by
            # the idempotent futures.
            with self.watchdog.guard(
                "serve_execute", self.resilience.execute_timeout_s,
                on_timeout=lambda d, c=live[0].cell, lv=list(live):
                    self._on_hang(c, d, lv),
            ):
                self._execute_live(live)
        finally:
            with self._lock:
                self._inflight = []
            if rec is not None:
                rec.end("serve.batch")
                rec.counter("serve.queue", {"depth": len(self._queue)})

    def _lane_stack_mode(self) -> str:
        """Effective lane-stack routing: env kill switch > serve context.
        Values are normalized (case/whitespace); an unrecognized value at
        dispatch time disables the stacked path (kill-switch-biased — a
        typo'd override must never silently keep the feature on), while
        an invalid *configured* value raises at engine construction."""
        import os

        mode = (
            os.environ.get("KAMINPAR_TPU_LANE_STACK", "")
            or getattr(self.serve, "lane_stack", "off")
        ).strip().lower()
        return mode if mode in ("auto", "on", "off") else "off"

    def _lanestack_fallback(self, reason: str, warn: bool) -> None:
        """Count one lane-stack fallback to the per-graph loop and, when
        ``warn``, surface the reason as a RuntimeWarning."""
        self.stats_.bump("lanestack_fallbacks")
        if warn:
            import warnings

            warnings.warn(
                f"kaminpar_tpu serve: {reason}; falling back to the "
                "per-graph loop.",
                RuntimeWarning,
                stacklevel=3,
            )

    def _try_lanestacked(
        self, live: List[ServeRequest]
    ) -> Optional[List[ServeRequest]]:
        """Run the whole batch as ONE vmapped lane-stacked program
        (serve/lanestack.py) when routing and eligibility allow; returns
        the fulfilled requests, or None to fall back to the per-graph loop
        (fallbacks are counted, and warned under ``lane_stack="on"``)."""
        mode = self._lane_stack_mode()
        if mode == "off" or (mode != "on" and len(live) < 2):
            return None
        cell_key = (
            live[0].cell.n_bucket, live[0].cell.m_bucket, live[0].cell.k
        )
        breaker = self.breakers.get("lanestack", cell_key)
        if not breaker.allow():
            # Breaker open (round 17, generalizing the round-11 latch):
            # skip the doomed stacked attempt — the demotion counter keeps
            # surfacing the lost parallelism, the trip itself already
            # warned, and the post-cooldown half-open probe re-arms the
            # stacked path without an engine restart.
            self.stats_.bump("lanestack_fallbacks")
            self.breakers.record_demotion(
                "lanestack", "circuit breaker open", warn=False
            )
            return None
        # Per-request constraint overrides (and non-strong quality tiers)
        # are outside the lockstep envelope: the stacked pipeline computes
        # every lane's caps from (k, epsilon), which the shape cell
        # already holds fixed, on the full-refinement chain.
        if any(
            r.max_block_weights is not None
            or r.min_block_weights is not None
            or r.min_epsilon
            or r.quality != "strong"
            for r in live
        ) or len({r.epsilon for r in live}) != 1:
            self._lanestack_fallback(
                "lane_stack=on but the batch carries per-request "
                "constraint overrides or mixed epsilons",
                warn=mode == "on",
            )
            return None
        from ..utils import compile_stats
        from .lanestack import LaneStackUnsupported, run_lanestacked

        pre_compiles = compile_stats.compile_time_snapshot()["compile_events"]
        t0 = time.perf_counter()
        try:
            with self.runtime.activate():
                parts, report = run_lanestacked(
                    self._solver.ctx, [r.graph for r in live],
                    live[0].k, live[0].epsilon,
                    trace_lane=self.name,
                )
        except LaneStackUnsupported as exc:
            self._lanestack_fallback(
                f"lane_stack=on but the batch is outside the lane-stack "
                f"envelope ({exc})",
                warn=mode == "on",
            )
            return None
        except Exception as exc:  # noqa: BLE001 — a lane-stack failure must
            # not reject a batch the per-graph loop can still serve; fall
            # back LOUDLY in every mode (the per-graph results remain
            # correct, the warning and counter surface the lost
            # parallelism).  The failure is classified and recorded on the
            # per-cell lanestack breaker; tripping it skips the doomed
            # attempt on later batches until the half-open probe recovers.
            from ..resilience.errors import classify

            err = classify(exc, site="lanestack")
            self._lanestack_fallback(
                f"lane-stacked execution failed "
                f"({err.failure_class}: {exc})",
                warn=True,
            )
            self.breakers.record_demotion(
                "lanestack", err.failure_class, warn=False
            )
            if breaker.record_failure():
                import warnings

                warnings.warn(
                    "kaminpar_tpu serve: lane-stacked execution failed on "
                    f"{breaker.threshold} consecutive batches in cell "
                    f"{cell_key} — disabling the stacked path for this "
                    "cell (the per-graph loop keeps serving; a half-open "
                    f"probe re-arms it after {breaker.cooldown_s}s).",
                    RuntimeWarning,
                    stacklevel=2,
                )
            return None
        wall = time.perf_counter() - t0
        if breaker.record_success():
            self.breakers.record_restoration("lanestack")
        # The stacked path serves these requests INSTEAD of the per-graph
        # loop, so it must also report the cell breaker's outcome — a
        # half-open cell probe served stacked would otherwise never close
        # the breaker and pin a healthy cell at one-probe-per-cooldown.
        cbr = self.breakers.get("cell", cell_key)
        if cbr.record_success():
            self.breakers.record_restoration("cell")
        # Key warm accounting on what this batch ACTUALLY dispatched: the
        # runner's recorded layout key (level-0 stack buckets + per-level
        # layout signatures x lane counts) with (k, epsilon) — the request
        # cell alone can't name the executables, because the isolated-node
        # strip moves work graphs across buckets and cohort splits change
        # lane counts.
        stack_key = (report.layout_key, live[0].k, live[0].epsilon)
        compiled = (
            stack_key not in self._warm_stack_keys
            and compile_stats.compile_time_snapshot()["compile_events"]
            > pre_compiles
        )
        # The submit-time warm flag covers the per-graph (bucket, k)
        # executable; a stacked batch's warmth is the lane-stack cell's —
        # correct the accounting in BOTH directions so warm_hit tracks
        # whether this request's dispatch actually avoided a compile
        # spike.  Gating the demotion on the engine-local key set keeps
        # compiles raised by OTHER engines/facades in the process (the
        # census is global) from demoting a batch whose stacked cell this
        # engine already ran.
        for req in live:
            if compiled and req.warm_hit:
                req.warm_hit = False
                self.stats_.bump("warm_hits", -1)
                self.stats_.bump("warm_misses")
            elif not compiled and not req.warm_hit:
                req.warm_hit = True
                self.stats_.bump("warm_hits")
                self.stats_.bump("warm_misses", -1)
        self._warm_stack_keys.add(stack_key)
        share = wall / len(live)
        self.stats_.bump("lanestacked_batches")
        self.stats_.bump("lanestacked_lanes", len(live))
        self.stats_.bump("lanestack_splits", report.splits)
        lane_cohorts = getattr(report, "lane_cohorts", ()) or ()
        for i, req in enumerate(live):
            # One stacked program serves all lanes; each request's execute
            # share is the batch wall over occupancy, and the rest of the
            # stacked wall counts as queue wait so queue_wait + execute
            # still covers the full submit->resolve wall (the per-graph
            # loop's percentile invariant).
            req.queue_wait_s = time.monotonic() - req.enqueue_t - share
            req.partition = parts[i]
            req.caps = report.caps[i]
            req.execute_s = share
            req.service_s = wall
            # Lane-stack lifecycle point: which cohort of the stacked
            # program this request's lane rode (cohort splits re-bucket
            # lanes whose work graphs left the request cell).
            self._trace_event(
                req, "lanestack", lane=i,
                cohort=(int(lane_cohorts[i])
                        if i < len(lane_cohorts) else 0),
                cohorts=report.cohorts, lanes=report.lanes,
                splits=report.splits,
            )
        return list(live)

    def _request_solver(self, req: ServeRequest):
        """The solver serving this request, after the quality ladder rung:
        explicit ``quality="fast"`` requests take the trimmed solver; a
        "strong" request is demoted to it when the cell's quality breaker
        is open (capacity-class failures tripped it) — counted, warned
        once, and restored by the half-open probe."""
        if req.quality == "fast":
            return self._get_fast_solver(), False
        key = (req.cell.n_bucket, req.cell.m_bucket, req.cell.k)
        qbreaker = self.breakers.get("quality_strong", key)
        if not qbreaker.allow():
            self.stats_.bump("demoted_quality")
            self.breakers.record_demotion(
                "quality_strong", "capacity pressure in this cell"
            )
            # Demotion-ladder lifecycle point: the quality_strong rung
            # served this strong request with the fast tier.
            self._trace_event(req, "demote", rung="quality_strong",
                              served="fast")
            return self._get_fast_solver(), False
        return self._solver, True

    def _get_fast_solver(self):
        """Lazily-built trimmed-refinement solver: the balancer+LP chain
        with halved LP sweeps and single-rep extension — the same
        deterministic pipeline shape, a lighter quality tier."""
        if self._fast_solver is None:
            from ..context import RefinementAlgorithm
            from ..kaminpar import KaMinPar

            fast = copy.deepcopy(self.ctx)
            keep = (
                RefinementAlgorithm.OVERLOAD_BALANCER,
                RefinementAlgorithm.LP,
                RefinementAlgorithm.UNDERLOAD_BALANCER,
                RefinementAlgorithm.GREEDY_BALANCER,
            )
            fast.refinement.algorithms = tuple(
                a for a in fast.refinement.algorithms if a in keep
            ) or (RefinementAlgorithm.OVERLOAD_BALANCER,
                  RefinementAlgorithm.LP)
            fast.refinement.lp.num_iterations = max(
                1, fast.refinement.lp.num_iterations // 2
            )
            fast.initial_partitioning.nested_extension_reps = 1
            fast.initial_partitioning.device_extension_reps = 1
            self._fast_solver = KaMinPar(fast)
        return self._fast_solver

    def _execute_live(self, live: List[ServeRequest]) -> None:
        from ..resilience.errors import classify
        from ..resilience.faults import maybe_inject

        ok = self._try_lanestacked(live)
        stacked = ok is not None
        if ok is None:
            ok = []
            for req in live:
                # Queue wait runs until THIS request's execution starts, so
                # a late batch member's wait includes in-batch serialization
                # — reported percentiles must cover the full submit->resolve
                # wall.
                req.queue_wait_s = time.monotonic() - req.enqueue_t
                t0 = time.perf_counter()
                key = (req.cell.n_bucket, req.cell.m_bucket, req.cell.k)
                # Provisional tier for the except path (a fault can fire
                # before _request_solver resolves the actual tier).
                strong = req.quality == "strong"
                try:
                    maybe_inject("execute", site="engine_request")
                    solver, strong = self._request_solver(req)
                    req.quality_served = "strong" if strong else "fast"
                    # The warm facade runs the *identical* code path a cold
                    # sequential KaMinPar.compute_partition runs (including
                    # its per-call RNG reseed), so per-graph results are
                    # bit-identical to single-graph runs by construction.
                    solver.set_graph(req.graph)
                    req.partition = solver.compute_partition(
                        req.k, req.epsilon, req.max_block_weights,
                        req.min_epsilon, req.min_block_weights,
                    )
                    req.caps = np.asarray(
                        solver.ctx.partition.max_block_weights,
                        dtype=np.int64,
                    ).copy()
                    req.execute_s = time.perf_counter() - t0
                    ok.append(req)
                    if not req.future.done():
                        # A done future means the watchdog already rejected
                        # this request as hung and TRIPPED the breaker —
                        # the late-returning dispatch must not record a
                        # success that would silently close it (the next
                        # request would re-enter the same hang).
                        cbr = self.breakers.get("cell", key)
                        if cbr.record_success():
                            self.breakers.record_restoration("cell")
                        if strong:
                            qbr = self.breakers.get("quality_strong", key)
                            if qbr.record_success():
                                self.breakers.record_restoration(
                                    "quality_strong"
                                )
                except Exception as exc:  # noqa: BLE001 — per-request isolation
                    # Route through the ONE classifier (round 17): callers
                    # get a typed failure, and the failure class picks the
                    # breaker — capacity pressure trips the quality rung
                    # (later strong requests demote to fast), everything
                    # else trips the cell breaker (enough repeats poison
                    # the cell at admission).  A False reject means the
                    # watchdog already force-resolved this future AND
                    # recorded the failure + breaker trip — don't
                    # double-count the late arrival.
                    err = classify(exc, site="engine_request")
                    if req.future._reject(err):
                        if err.failure_class == "capacity-exceeded" and strong:
                            self.breakers.get(
                                "quality_strong", key
                            ).record_failure()
                        else:
                            # Fast-tier capacity failures land here too:
                            # a cell that OOMs even under the trimmed
                            # solver has no further rung to demote to —
                            # it must poison at admission, not burn a
                            # doomed dispatch per request.
                            self.breakers.get("cell", key).record_failure()
                        exec_s = time.perf_counter() - t0
                        self._trace_event(
                            req, "error", final=self._final_error(err),
                            failure_class=err.failure_class,
                            site="engine_request",
                        )
                        self.stats_.record_request(
                            req.queue_wait_s, exec_s, failed=True,
                        )
                        if self._slo is not None:
                            self._slo.record_request(
                                req.quality_served or req.quality,
                                req.queue_wait_s + exec_s, ok=False,
                            )
        if not ok:
            return

        # Whole-batch quality metrics in ONE dispatch over the packed
        # disjoint-union buffer + one batched readback (serve/batching.py).
        t_metrics = time.perf_counter()
        cuts, bws = batched_metrics(
            pack_graphs([r.graph for r in ok]),
            [r.partition for r in ok],
            ok[0].k,
            pad_to=self.serve.max_batch,
        )
        metrics_share_s = (time.perf_counter() - t_metrics) / len(ok)
        from ..telemetry import trace as ttrace

        rec = ttrace.active()
        for i, req in enumerate(ok):
            req.execute_s += metrics_share_s
            if not stacked:
                # A stacked batch traces only lane-stack executables — it
                # does not warm the per-graph (bucket, k) cell, so marking
                # it here would report a later lone request in this cell
                # as a warm hit while it pays the full per-graph compile
                # (the stacked path tracks its own _warm_stack_keys).
                self._note_warm(
                    req.cell, req.quality_served or req.quality
                )
            feasible = bool(np.all(bws[i] <= req.caps))
            resolved = req.future._resolve(ServeResult(
                partition=req.partition,
                cut=int(cuts[i]),
                feasible=feasible,
                batch_size=len(ok),
                queue_wait_s=req.queue_wait_s,
                execute_s=req.execute_s,
                warm_hit=req.warm_hit,
                request_id=req.id,
            ))
            if not resolved:
                # The watchdog already force-resolved this future (the
                # dispatch was abandoned as hung and came back late): the
                # failure was recorded there — don't double-count.
                continue
            self.stats_.record_request(
                req.queue_wait_s, req.execute_s, service_s=req.service_s
            )
            self._trace_event(
                req, "resolve", final=True, cut=int(cuts[i]),
                feasible=feasible, batch=len(ok),
                quality=req.quality_served or req.quality,
                queue_wait_ms=round(req.queue_wait_s * 1e3, 2),
                execute_ms=round(req.execute_s * 1e3, 2),
            )
            if self._slo is not None:
                self._slo.record_request(
                    req.quality_served or req.quality,
                    req.queue_wait_s + req.execute_s, ok=True,
                )
            if rec is not None:
                rec.instant(
                    "serve.resolve", request_id=req.id, cut=int(cuts[i]),
                    feasible=feasible,
                    queue_wait_ms=round(req.queue_wait_s * 1e3, 2),
                    execute_ms=round(req.execute_s * 1e3, 2),
                )

    # -- observability -----------------------------------------------------

    def steer_signals(self) -> dict:
        """Cheap live serving signals for the fleet router (round 18) —
        queue depth, the unamortized service-time EMA, p99 execute
        seconds, open-breaker counts, watchdog fires — WITHOUT the full
        snapshot's compile/sync census cost.  Pure host reads."""
        return {
            "running": self._running,
            "queue_depth": len(self._queue),
            "ema_service_s": self.stats_.service_time_estimate(),
            "p99_execute_s": self.stats_.execute_p99_s(),
            "open_breakers": self.breakers.open_count(),
            "open_cell_breakers": self.breakers.open_count("cell"),
            "watchdog_timeouts": self.stats_.counter("watchdog_timeouts"),
            "max_batch": self.serve.max_batch,
            # SLO control pressure (round 20): max(0, worst_burn - 1),
            # briefly memoized — 0.0 whenever objectives are disarmed, so
            # the steering score is unchanged unless a deployment arms
            # them (bit-identity: control input only).
            "slo_pressure": (
                self._slo.pressure() if self._slo is not None else 0.0
            ),
        }

    def cell_depth(self, cell: ShapeCell) -> int:
        """Queued same-cell requests (the router's batch-join signal)."""
        return self._queue.cell_depth(cell)

    def capacity_verdict(self, graph, k: int) -> bool:
        """Would the admission preflight accept this request?  A pure
        non-raising, non-counting probe for the fleet router's steering
        score — per-replica ceilings can differ, so a request a small
        replica must reject may still be steerable to a bigger one.  True
        when no ceiling is knowable (preflight off).  Same invocation as
        the admission path (:meth:`_run_preflight`)."""
        try:
            self._run_preflight(graph, k)
        except CapacityError:
            return False
        return True

    def warmup_cell_counts(self) -> dict:
        """Inherited vs locally-compiled warmup cells (round 18 warm-cache
        inheritance; printed by ``tools warmup --fleet``)."""
        inherited = sum(
            1 for r in self.warmup_report if r.get("inherited")
        )
        return {
            "inherited": inherited,
            "local": len(self.warmup_report) - inherited,
        }

    def stats(self) -> dict:
        """Structured snapshot: queue depth, admission/reject/timeout
        counts, batch occupancy, warm-cache hit rate, latency percentiles,
        plus the compile-shape and blocking-transfer censuses."""
        snap = self.stats_.snapshot(queue_depth=len(self._queue))
        snap["running"] = self._running
        snap["warm_cells"] = len(self._warm_cells)
        snap["warmup"] = list(self.warmup_report)
        snap["warmup_cells"] = self.warmup_cell_counts()
        # Resilience surface (round 17): this engine's breaker registry
        # (lanestack/cell/quality rungs), the process-global pipeline
        # registry (lp_pallas/ip_device/device_decode rungs), the
        # watchdog's guard/fire census + dossier heads, and the chaos
        # harness's injection counters.
        from ..resilience import breakers as rbreakers
        from ..resilience import faults as rfaults

        snap["resilience"] = {
            "engine": self.breakers.snapshot(),
            "pipeline": rbreakers.global_registry().snapshot(),
            "watchdog": self.watchdog.snapshot(),
            "faults": rfaults.snapshot(),
        }
        # Crash-safe journal surface (round 19, serve/journal.py):
        # append/fsync counts of the live journal file — the replay and
        # resolution counters ride the standard counter block above.
        if self._journal is not None:
            snap["journal"] = self._journal.snapshot()
        # SLO burn surface (round 20, telemetry/slo.py): per-window
        # error-budget burn rates + the control pressure the fleet
        # steering/autoscale consume.  Pure host scan of the event ring,
        # counted under slo_eval.
        from ..utils.timer import scoped_timer

        with scoped_timer("slo_eval"):
            snap["slo"] = (
                self._slo.summary() if self._slo is not None
                else {"armed": False}
            )
        snap["reqtrace"] = self.reqtrace.snapshot()
        return snap

    def metrics_text(self) -> str:
        """Prometheus text exposition of the serving metrics (ISSUE 5):
        queue depth, admission/reject/timeout counts, batch occupancy,
        warm-cache hit rate, p50/p90/p99 latencies, and the compile-shape /
        blocking-transfer censuses.  The serve CLI's ``--metrics-port``
        serves this at ``/metrics``; scrape-friendly and dependency-free
        (telemetry/prometheus.py)."""
        from ..telemetry import prometheus
        from ..utils import compile_stats

        families = self.stats_.prometheus_families(
            queue_depth=len(self._queue),
            running=self._running,
            warm_cells=len(self._warm_cells),
        )
        # Executable census families (ISSUE 12): per-cell flops / peak /
        # temp bytes from XLA's own analyses, exported beside the serve
        # metrics so operators scrape what each executable WOULD do.
        families.extend(compile_stats.census_prometheus_families())
        # Resilience families (round 17): breaker states/trips, ladder
        # demotions + restorations, chaos injections — merged over this
        # engine's registry and the process-global pipeline registry.
        from ..resilience import breakers as rbreakers

        families.extend(rbreakers.prometheus_families(
            self.breakers, rbreakers.global_registry()
        ))
        families.append((
            "kaminpar_resilience_watchdog_fired_total", "counter",
            "Execution-watchdog deadline overruns converted into breaker "
            "trips + typed future resolutions",
            [({}, self.watchdog.fired)],
        ))
        # Warm-cache inheritance census (round 18): how many warmup cells
        # this replica inherited from the fleet vs compiled locally.
        cells = self.warmup_cell_counts()
        families.append((
            "kaminpar_serve_warmup_cells_total", "counter",
            "Warmup-report cells by source: inherited from the fleet's "
            "warm state vs locally traced/compiled",
            [({"source": "inherited"}, cells["inherited"]),
             ({"source": "local"}, cells["local"])],
        ))
        # SLO burn families (round 20, telemetry/slo.py) — empty unless
        # the ServeContext arms at least one objective.
        from ..telemetry import slo as slo_mod

        families.extend(slo_mod.prometheus_families(self._slo))
        return prometheus.render(families)
